"""IO layer tests: reference-format binaries, ASCII, async writer,
checkpoint/resume (the subsystem the reference lacks, SURVEY §5)."""

import os

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.utils import io as tio


def test_binary_roundtrip(tmp_path):
    u = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    p = str(tmp_path / "u.bin")
    tio.save_binary(u, p)
    # layout: x fastest (SaveBinary3D, Tools.c:110) == C-order ravel
    raw = np.fromfile(p, dtype=np.float32)
    np.testing.assert_array_equal(raw, u.ravel())
    back = tio.load_binary(p, u.shape)
    np.testing.assert_array_equal(back, u)


def test_ascii_matches_reference_format(tmp_path):
    u = np.array([1.0, 0.5, 1e-7, 3.14159])
    p = str(tmp_path / "u.txt")
    tio.save_ascii(u, p)
    lines = open(p).read().strip().split("\n")
    assert lines == ["1", "0.5", "1e-07", "3.14159"]


def test_async_writer(tmp_path):
    snaps = [np.full((8, 8), i, np.float32) for i in range(5)]
    with tio.AsyncBinaryWriter() as w:
        for i, s in enumerate(snaps):
            w.submit(s, str(tmp_path / f"s{i}.bin"))
    for i, s in enumerate(snaps):
        back = tio.load_binary(str(tmp_path / f"s{i}.bin"), s.shape)
        np.testing.assert_array_equal(back, s)


def test_checkpoint_resume(tmp_path):
    grid = Grid.make(17, 17, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float64")
    solver = DiffusionSolver(cfg)
    s = solver.run(solver.initial_state(), 3)
    p = str(tmp_path / "ck.npz")
    tio.save_checkpoint(p, s, grid=grid)
    restored = tio.load_checkpoint(p)
    np.testing.assert_array_equal(np.asarray(restored.u), np.asarray(s.u))
    assert float(restored.t) == float(s.t)
    # resuming and stepping produces the same trajectory as uninterrupted
    a = solver.run(restored, 2)
    b = solver.run(s, 2)
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))


def test_native_library_is_used_if_built():
    lib = tio._load_native()
    here = os.path.dirname(os.path.dirname(os.path.abspath(tio.__file__)))
    built = os.path.exists(os.path.join(here, "..", "native", "libtpucfd_io.so"))
    if built:
        assert lib, "native lib exists but ctypes binding failed"
    else:
        pytest.skip("native lib not built (numpy fallback in use)")


def test_ckpt_roundtrip_and_header(tmp_path):
    """.ckpt format: atomic save + CRC-verified load (native path when
    built, numpy mirror otherwise — bytes identical either way)."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    u = jnp.asarray(np.random.default_rng(3).standard_normal((6, 5, 4)),
                    jnp.float32)
    s = SolverState(u=u, t=jnp.asarray(0.625), it=jnp.asarray(42))
    p = str(tmp_path / "state.ckpt")
    tio.save_checkpoint(p, s)
    r = tio.load_checkpoint(p)
    np.testing.assert_array_equal(np.asarray(r.u), np.asarray(u))
    assert float(r.t) == 0.625 and int(r.it) == 42
    assert not os.path.exists(p + ".tmp")  # atomic: no droppings
    # header is the documented layout regardless of which writer ran
    with open(p, "rb") as f:
        assert f.read(8) == b"TPCFDCKP"


def test_ckpt_numpy_and_native_writers_agree(tmp_path):
    """When the native library is built, its bytes must equal the numpy
    mirror's (one on-disk format, not two)."""
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    if not tio._load_native() or not hasattr(tio._load_native(),
                                             "checkpoint_save"):
        pytest.skip("native library not built")
    u = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    s = SolverState(u=u, t=jnp.asarray(1.5), it=jnp.asarray(7))
    p_native = str(tmp_path / "native.ckpt")
    tio.save_checkpoint(p_native, s)
    native_bytes = open(p_native, "rb").read()
    # force the numpy mirror
    saved = tio._native
    try:
        tio._native = False
        p_py = str(tmp_path / "python.ckpt")
        tio.save_checkpoint(p_py, s)
        py_bytes = open(p_py, "rb").read()
    finally:
        tio._native = saved
    assert native_bytes == py_bytes


def test_ckpt_detects_corruption(tmp_path):
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    u = jnp.asarray(np.ones((8, 8), np.float32))
    p = str(tmp_path / "c.ckpt")
    tio.save_checkpoint(p, SolverState(u=u, t=jnp.asarray(0.0),
                                       it=jnp.asarray(0)))
    blob = bytearray(open(p, "rb").read())
    blob[100] ^= 0xFF  # flip one payload byte
    open(p, "wb").write(bytes(blob))
    with pytest.raises(IOError, match="CRC"):
        tio.load_checkpoint(p)
    # truncation is also caught
    open(p, "wb").write(bytes(blob[:70]))
    with pytest.raises(IOError, match="truncated"):
        tio.load_checkpoint(p)


def test_rotate_checkpoints(tmp_path):
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    u = jnp.asarray(np.zeros((4,), np.float32))
    for i in range(5):
        tio.save_checkpoint(
            str(tmp_path / f"checkpoint_{i:06d}.ckpt"),
            SolverState(u=u, t=jnp.asarray(float(i)), it=jnp.asarray(i)),
        )
    # non-checkpoint files with the prefix must never be touched
    (tmp_path / "checkpoint_notes.txt").write_text("keep me")
    # a user .ckpt whose stem is not an iteration number (e.g. a manual
    # "best" save) is not rotation-managed and must survive
    tio.save_checkpoint(
        str(tmp_path / "checkpoint_best.ckpt"),
        SolverState(u=u, t=jnp.asarray(0.0), it=jnp.asarray(0)),
    )
    tio.rotate_checkpoints(str(tmp_path), keep=2)
    left = sorted(f for f in os.listdir(tmp_path) if f.endswith(".ckpt"))
    assert left == [
        "checkpoint_000003.ckpt",
        "checkpoint_000004.ckpt",
        "checkpoint_best.ckpt",
    ]
    assert (tmp_path / "checkpoint_notes.txt").exists()
    # keep=0 means keep everything
    tio.rotate_checkpoints(str(tmp_path), keep=0)
    assert len(sorted(f for f in os.listdir(tmp_path) if f.endswith(".ckpt"))) == 3


def test_print_field_layout():
    """Print2D/Print3D console-dump analog: rows per line, blank line
    between z-slices."""
    import io as _io

    buf = _io.StringIO()
    tio.print_field(np.arange(12).reshape(2, 2, 3), file=buf)
    blocks = buf.getvalue().strip().split("\n\n")
    assert len(blocks) == 2
    assert blocks[0].splitlines()[0].split() == ["0.00", "1.00", "2.00"]


# --------------------------------------------------------------------- #
# Per-shard checkpointing (.ckptd directories): each process writes only
# its addressable shards + a layout manifest; resume reassembles under
# ANY decomposition. Lifts the documented gather-to-one-host scale limit
# of save_checkpoint (and exceeds the reference, which gathers to rank 0
# and has no restart at all, main.c:326-335).
# --------------------------------------------------------------------- #


def _sharded_state(devices, mesh_axes, decomp_map, shape=(16, 16, 16)):
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(*reversed(shape), lengths=4.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32")
    solver = DiffusionSolver(
        cfg, mesh=make_mesh(mesh_axes), decomp=Decomposition.of(decomp_map)
    )
    return solver, solver.run(solver.initial_state(), 3)


def test_sharded_checkpoint_roundtrip_same_decomp(devices, tmp_path):
    solver, state = _sharded_state(devices, {"dz": 4}, {0: "dz"})
    d = str(tmp_path / "ck.ckptd")
    tio.save_checkpoint_sharded(d, state, grid=solver.grid)
    # one .ckpt per shard, a per-process manifest, a global manifest
    names = sorted(os.listdir(d))
    assert "manifest.json" in names and "manifest_p0.json" in names
    assert sum(n.startswith("shard_") for n in names) == 4
    back = tio.load_checkpoint_sharded(d, sharding=solver.sharding())
    np.testing.assert_array_equal(np.asarray(back.u), np.asarray(state.u))
    assert float(back.t) == float(state.t) and int(back.it) == int(state.it)
    # the reassembled array actually carries the requested sharding
    assert back.u.sharding.is_equivalent_to(solver.sharding(), back.u.ndim)


def test_sharded_checkpoint_resume_different_decomp(devices, tmp_path):
    """Saved under z-slabs, resumed under (dz, dy) pencils AND unsharded:
    the manifest layout makes the decomposition a free choice at load."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    solver, state = _sharded_state(devices, {"dz": 8}, {0: "dz"})
    d = str(tmp_path / "ck.ckptd")
    tio.save_checkpoint_sharded(d, state, grid=solver.grid)

    pencil = Decomposition.of({0: "dz", 1: "dy"}).sharding(
        make_mesh({"dz": 2, "dy": 2}), 3
    )
    back = tio.load_checkpoint_sharded(d, sharding=pencil)
    np.testing.assert_array_equal(np.asarray(back.u), np.asarray(state.u))

    local = tio.load_checkpoint_sharded(d)  # no sharding: plain assembly
    np.testing.assert_array_equal(np.asarray(local.u), np.asarray(state.u))


def test_sharded_checkpoint_detects_missing_shard(devices, tmp_path):
    solver, state = _sharded_state(devices, {"dz": 4}, {0: "dz"})
    d = str(tmp_path / "ck.ckptd")
    tio.save_checkpoint_sharded(d, state, grid=solver.grid)
    victim = next(n for n in os.listdir(d) if n.startswith("shard_"))
    os.remove(os.path.join(d, victim))
    with pytest.raises(IOError):
        tio.load_checkpoint_sharded(d)


def test_sharded_checkpoint_meta_and_unsharded_array(tmp_path):
    """Plain (unsharded) arrays write a single-shard directory, and the
    manifest carries the grid/physics meta the resume validation reads."""
    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    grid = Grid.make(12, 10, lengths=4.0)
    u = np.arange(120, dtype=np.float32).reshape(10, 12)
    st = SolverState(u=u, t=np.float64(0.5), it=np.int64(7))
    d = str(tmp_path / "ck.ckptd")
    tio.save_checkpoint_sharded(d, st, grid=grid, physics={"diffusivity": 2.0})
    meta = tio.read_checkpoint_meta(d)
    assert meta["bounds"] == [list(b) for b in grid.bounds]
    assert meta["physics"] == {"diffusivity": 2.0}
    back = tio.load_checkpoint(d)  # load_checkpoint dispatches on dirs
    np.testing.assert_array_equal(np.asarray(back.u), u)
    assert int(back.it) == 7


def test_rotate_checkpoints_handles_ckptd_dirs(tmp_path):
    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    for i in (2, 4, 6):
        st = SolverState(u=np.zeros((4, 4), np.float32),
                         t=np.float64(i), it=np.int64(i))
        tio.save_checkpoint_sharded(
            str(tmp_path / f"checkpoint_{i:06d}.ckptd"), st
        )
    tio.rotate_checkpoints(str(tmp_path), keep=1)
    left = sorted(os.listdir(tmp_path))
    assert left == ["checkpoint_000006.ckptd"]


def test_single_file_checkpoint_load_honors_sharding(tmp_path):
    """load_checkpoint(path, sharding=...) on a single-file checkpoint
    must place the restored array on the requested sharding (previously
    the argument was silently ignored for non-directory paths and only
    the CLI driver compensated — ADVICE r4)."""
    import jax
    import jax.numpy as jnp

    from multigpu_advectiondiffusion_tpu.models.state import SolverState
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    mesh = make_mesh({"dz": 2})
    sh = Decomposition.slab("dz").sharding(mesh, 3)
    u = jnp.asarray(np.arange(8 * 6 * 6, dtype=np.float32).reshape(8, 6, 6))
    for name in ("s.ckpt", "s.npz"):
        p = str(tmp_path / name)
        tio.save_checkpoint(p, SolverState(u=u, t=jnp.asarray(0.5),
                                           it=jnp.asarray(3)))
        back = tio.load_checkpoint(p, sharding=sh)
        assert back.u.sharding.is_equivalent_to(sh, back.u.ndim)
        np.testing.assert_array_equal(np.asarray(back.u), np.asarray(u))
