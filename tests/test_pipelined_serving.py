"""Zero-copy pipelined serving (ISSUE 19).

Tier-1 coverage of the four tentpole layers: buffer donation through
the batched ensemble dispatch (bit-exact vs undonated on diffusion AND
Burgers, reuse-after-donate a loud error), the pipelined slice loop
(bit-exact vs the synchronous server at B in {1, 8}), group-commit
journaling (durability semantics, batch accounting, the bounded-latency
window, and the ack barrier — an injected ack-before-fsync fault leaves
detectable acked-but-unjournaled orphans), and the real-SIGKILL chaos
case under --pipeline --group-commit: restart replays to exactly-once
with ZERO acked-but-unjournaled requests.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import Grid
from multigpu_advectiondiffusion_tpu.models import registry
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver
from multigpu_advectiondiffusion_tpu.resilience import faults
from multigpu_advectiondiffusion_tpu.service.journal import (
    Journal,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.requests import (
    ALLOWED_REQUEST_TRANSITIONS,
    REQUEST_TERMINAL_STATES,
    RequestSpec,
    submit_request_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.server import RequestServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the tier-1 serving shape (see tests/test_serving.py): diffusion's
# analytic Gaussian starts at t0 = 0.1, so horizons must exceed it
N = [12, 12]
T_END = 0.18  # ~12 steps at this grid's stability dt


def _spec(rid, **kw) -> RequestSpec:
    base = dict(model="diffusion", n=list(N), t_end=T_END,
                ic="gaussian")
    base.update(kw)
    return RequestSpec(request_id=rid, **base)


def _result_bits(root, rid) -> bytes:
    with open(os.path.join(root, "requests", rid, "result.bin"),
              "rb") as f:
        return f.read()


def _acked_but_unjournaled(root):
    """Request ids whose verdict.json says done but whose journal has
    no done transition — the inconsistency the group-commit ack barrier
    must make impossible (and the injected fault must make visible)."""
    records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
    journaled = {r.get("job") for r in records
                 if r.get("type") == "state" and r.get("to") == "done"}
    acked = set()
    for p in glob.glob(os.path.join(root, "requests", "*",
                                    "verdict.json")):
        with open(p) as f:
            v = json.load(f)
        if v.get("status") == "done":
            acked.add(os.path.basename(os.path.dirname(p)))
    return sorted(acked - journaled)


# --------------------------------------------------------------------- #
# Layer 1: buffer donation through the batched dispatch
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("family,overrides,t_end", [
    ("diffusion", [{}, {"diffusivity": 1.3}], 0.16),
    ("burgers", [{}, {"cfl": 0.4}], 0.2),
])
def test_donated_advance_bit_exact(family, overrides, t_end):
    """The acceptance criterion: a donated dispatch computes the SAME
    bits as the undonated one — donation changes buffer lifetime, never
    arithmetic — on both model families."""
    fam = registry.get(family)
    cfg = fam.config_cls(grid=Grid.make(24, 24))
    te = [float(t_end)] * len(overrides)

    ens = EnsembleSolver(fam.solver_cls, cfg, overrides)
    plain = ens.advance_to(ens.initial_state(), te, max_steps=64)
    donated = ens.advance_to(ens.initial_state(), te, max_steps=64,
                             donate=True)
    assert np.asarray(plain.it).tolist() == \
        np.asarray(donated.it).tolist()
    pu = np.asarray(plain.u)
    du = np.asarray(donated.u)
    assert pu.dtype == du.dtype
    assert (pu == du).all(), (
        f"{family}: donated dispatch changed bits "
        f"(max abs diff {np.max(np.abs(pu - du))})"
    )


def test_reuse_after_donate_raises():
    """The donated operand is consumed: touching the old state's ``u``
    after a donating dispatch must be a loud error on EVERY backend —
    including CPU, where XLA ignores the donation hint and the explicit
    post-dispatch delete supplies the semantics."""
    fam = registry.get("diffusion")
    cfg = fam.config_cls(grid=Grid.make(16, 16))
    ens = EnsembleSolver(fam.solver_cls, cfg, [{}, {"diffusivity": 1.3}])
    st = ens.initial_state()
    out = ens.advance_to(st, [0.14, 0.14], max_steps=8, donate=True)
    with pytest.raises(RuntimeError):
        np.asarray(st.u)
    # the NEW state and the old state's undonated scalars stay readable
    assert np.isfinite(np.asarray(out.u)).all()
    assert np.asarray(st.t).shape == (2,)
    assert np.asarray(st.it).shape == (2,)


# --------------------------------------------------------------------- #
# Layer 2: pipelined vs synchronous serving, bit-exact
# --------------------------------------------------------------------- #
def _serve(root, specs, **server_kw):
    for s in specs:
        submit_request_to_spool(root, s)
    srv = RequestServer(root, max_batch=8, slice_steps=4, fsync=False,
                        **server_kw)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.001)
    finally:
        srv.close()
    return out


@pytest.mark.parametrize("width", [1, 8])
def test_pipelined_bit_exact_vs_sync(tmp_path, width):
    """The same request set served by the synchronous loop and by the
    pipelined loop (donated buffers, depth 2, async finished-lane
    publish) publishes bit-identical results at B in {1, 8}."""
    specs = [
        _spec(f"p{i}", ic_params={"width": 0.08 + 0.01 * i})
        for i in range(width)
    ]
    sync_root = str(tmp_path / "sync")
    pipe_root = str(tmp_path / "pipe")
    out_sync = _serve(sync_root, specs, pipeline=False)
    out_pipe = _serve(pipe_root, specs, pipeline=True,
                      pipeline_depth=2)
    assert out_sync["states"].get("done") == width
    assert out_pipe["states"].get("done") == width
    for s in specs:
        assert _result_bits(sync_root, s.request_id) == \
            _result_bits(pipe_root, s.request_id), (
                f"{s.request_id}: pipelined serving changed the answer"
            )
    # the pipelined round actually dispatched ahead and published
    ev = [json.loads(l) for l in
          open(os.path.join(pipe_root, "serve_events.jsonl"))
          if l.strip()]
    assert any(e["kind"] == "pipeline" and e["name"] == "dispatch"
               for e in ev)
    assert any(e["kind"] == "pipeline" and e["name"] == "publish"
               for e in ev)
    assert any(e["kind"] == "pipeline" and e["name"] == "batch_idle"
               for e in ev)


# --------------------------------------------------------------------- #
# Layer 3: group-commit journaling
# --------------------------------------------------------------------- #
def test_group_commit_defers_fsync_until_barrier(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, group_commit_s=60.0) as j:
        r1 = j.append("note", msg="a")
        r2 = j.append("note", msg="b")
        # written + flushed (replayable NOW), but not fsync-durable
        assert r1["durable"] is False and r2["durable"] is False
        assert j.unsynced == 2
        assert not j.commit_due()
        assert j.maybe_commit() == 0  # window not elapsed: no fsync
        records, torn = Journal.replay(path)
        assert [r["msg"] for r in records] == ["a", "b"]
        assert torn == 0
        # the barrier fsyncs the whole batch and reports its size
        assert j.commit() == 2
        assert j.unsynced == 0
        assert j.last_commit_batch == 2
        assert j.commit() == 0  # idempotent


def test_group_commit_window_elapses(tmp_path):
    path = str(tmp_path / "j.jsonl")
    sizes = []
    with Journal(path, group_commit_s=0.02) as j:
        j.on_commit_batch = sizes.append
        j.append("note", msg="a")
        assert j.unsynced == 1
        time.sleep(0.03)
        # the bounded-latency window elapsed: the next append (or the
        # loop's maybe_commit) fsyncs without an explicit barrier
        rec = j.append("note", msg="b")
        assert rec["durable"] is True
        assert j.unsynced == 0
    assert sizes and sizes[0] == 2


def test_group_commit_zero_is_immediate(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path, group_commit_s=0.0) as j:
        assert j.append("note", msg="a")["durable"] is True
        assert j.unsynced == 0


def test_group_commit_close_flushes_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    sizes = []
    j = Journal(path, group_commit_s=60.0)
    j.on_commit_batch = sizes.append
    j.append("note", msg="tail")
    j.close()
    assert sizes == [1]  # close() is a barrier: no unsynced tail


def test_ack_before_fsync_fault_leaves_detectable_orphans(
        tmp_path, monkeypatch):
    """The gate's teeth, in-process: with the injected fault the server
    acks done BEFORE the journal record exists — the consistency check
    must see acked-but-unjournaled requests. Without the fault (the
    real ack barrier) the same check must see none."""
    specs = [_spec(f"f{i}", ic_params={"width": 0.08 + 0.01 * i})
             for i in range(2)]

    clean_root = str(tmp_path / "clean")
    _serve(clean_root, specs, pipeline=True, group_commit_s=0.005)
    assert _acked_but_unjournaled(clean_root) == []

    monkeypatch.setenv("TPUCFD_FAULT_ACK_BEFORE_FSYNC", "1")
    fault_root = str(tmp_path / "fault")
    _serve(fault_root, specs, pipeline=True, group_commit_s=0.005)
    orphans = _acked_but_unjournaled(fault_root)
    assert orphans == sorted(s.request_id for s in specs), (
        f"fault injection should orphan every ack, got {orphans}"
    )


# --------------------------------------------------------------------- #
# Layer 4: SIGKILL mid-group-commit chaos
# --------------------------------------------------------------------- #
_PIPELINED_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main(["serve-requests", "--root", sys.argv[2], "--until-idle",
      "--max-batch", "4", "--slice-steps", "2", "--poll", "0.01",
      "--pipeline", "--pipeline-depth", "2", "--group-commit-ms", "20"])
print("SERVE-WORKER-OK", flush=True)
'''


def _launch(tmp_path, tag, root):
    script = tmp_path / f"server_{tag}.py"
    script.write_text(_PIPELINED_WORKER)
    log = tmp_path / f"server_{tag}.log"
    handle = open(log, "w")
    proc = subprocess.Popen(
        [sys.executable, str(script), REPO, root],
        stdout=handle, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc, log, handle


@pytest.mark.chaos
def test_sigkill_mid_group_commit_replays_exactly_once(tmp_path):
    """SIGKILL a pipelined group-commit server mid-batch, restart it:
    every request reaches done exactly once, the journal linearizes
    complete, and — the group-commit contract — ZERO requests are
    acked-but-unjournaled at every point (the kill instant included:
    no verdict may exist without its fsynced done record)."""
    root = str(tmp_path / "killed")
    specs = [_spec(f"k{i}", t_end=0.5,
                   ic_params={"width": 0.08 + 0.02 * i})
             for i in range(4)]
    for s in specs:
        submit_request_to_spool(root, s)

    proc, log, handle = _launch(tmp_path, "victim", root)
    try:
        slices_seen = faults.kill_server_mid_batch(proc, root,
                                                   timeout=180.0)
        assert slices_seen >= 1
        proc.wait(timeout=30)
        assert proc.returncode == -9
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()

    # the kill instant: whatever was acked must already be journalled
    assert _acked_but_unjournaled(root) == [], (
        "SIGKILL caught an ack ahead of its fsync barrier"
    )

    proc, log, handle = _launch(tmp_path, "recovered", root)
    try:
        rc = proc.wait(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()
    assert rc == 0, f"recovered server rc={rc}:\n{log.read_text()[-2000:]}"

    records, torn = Journal.replay(os.path.join(root, "journal.jsonl"))
    assert verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        require_complete=True,
    ) == []
    for s in specs:
        dones = [r for r in records if r.get("type") == "state"
                 and r.get("job") == s.request_id
                 and r.get("to") == "done"]
        assert len(dones) == 1, (
            f"{s.request_id}: answered {len(dones)} times"
        )
    assert _acked_but_unjournaled(root) == []


# --------------------------------------------------------------------- #
# Satellite: the stdlib HTTP ingestion adapter
# --------------------------------------------------------------------- #
def test_http_adapter_submits_and_reads_results(tmp_path):
    import urllib.error
    import urllib.request

    root = str(tmp_path / "http")
    os.makedirs(root, exist_ok=True)
    srv = RequestServer(root, max_batch=4, slice_steps=4, fsync=False,
                        pipeline=True, http_port=0)
    try:
        port = srv.http_port
        assert port
        base = f"http://127.0.0.1:{port}"
        body = json.dumps({
            "request_id": "h1", "model": "diffusion", "n": N,
            "t_end": T_END, "ic": "gaussian",
            "ic_params": {"width": 0.09},
        }).encode()
        req = urllib.request.Request(
            f"{base}/requests", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 202
            assert json.load(resp)["request_id"] == "h1"
        # drive the serving loop until the request publishes
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            srv.tick()
            if os.path.exists(os.path.join(root, "requests", "h1",
                                           "verdict.json")):
                break
        with urllib.request.urlopen(f"{base}/requests/h1",
                                    timeout=10) as resp:
            assert json.load(resp)["status"] == "done"
        with urllib.request.urlopen(f"{base}/requests/h1/result.bin",
                                    timeout=10) as resp:
            bits = resp.read()
        assert bits == _result_bits(root, "h1")
        with urllib.request.urlopen(f"{base}/healthz",
                                    timeout=10) as resp:
            assert json.load(resp)["status"] == "ok"
        # path traversal is a 400, never a read
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/requests/..%2f..%2fjournal.jsonl", timeout=10
            )
        assert ei.value.code in (400, 404)
        # a malformed POST is a 400, not a crash
        bad = urllib.request.Request(f"{base}/requests",
                                     data=b"{not json")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
    finally:
        srv.close()
