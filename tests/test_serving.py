"""Continuous-batching request server (ISSUE 17).

The serving stack end to end: request specs/coalesce keys and the
journal-backed request queue; hardened spool ingest (torn mailbox
entries quarantined, never fatal — both the job spool and the request
spool); per-member ``advance_to_ensemble(max_steps=)`` slice-boundary
semantics (the batching engine's contract, unsharded and
member-sharded); the in-process server (coalesced dispatch,
backpressure shed, per-request failure isolation, divergence
forensics, priority preemption, memory-capped admission, late joins);
in-process crash recovery; and the real-SIGKILL chaos case
(``faults.kill_server_mid_batch``): restart replays the journal to
zero lost and zero duplicated requests, bit-exact against an
uninterrupted run.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.models.ensemble import EnsembleSolver
from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh
from multigpu_advectiondiffusion_tpu.resilience import faults
from multigpu_advectiondiffusion_tpu.service.journal import (
    Journal,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.queue import (
    JobQueue,
    JobSpec,
    ingest_spool,
    spool_dir,
    submit_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.requests import (
    ALLOWED_REQUEST_TRANSITIONS,
    REQUEST_TERMINAL_STATES,
    RequestQueue,
    RequestSpec,
    coalesce_key,
    ingest_request_spool,
    request_spool_dir,
    submit_request_to_spool,
)
from multigpu_advectiondiffusion_tpu.service.server import RequestServer
from multigpu_advectiondiffusion_tpu.utils.io import load_binary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the tier-1 serving shape: a grid small enough that a full batched
# compile + march is seconds on one CPU core, with enough steps that
# several bounded slices happen. The diffusion family's analytic
# Gaussian starts at t0 = 0.1 (heat3d.m:15) with dt ~ 6.6e-3 on this
# grid, so horizons are t0 + (steps * dt).
N = [12, 12]
T0 = 0.1
T_END = 0.18  # ~12 steps


def _spec(rid, **kw) -> RequestSpec:
    base = dict(model="diffusion", n=list(N), t_end=T_END,
                ic="gaussian")
    base.update(kw)
    return RequestSpec(request_id=rid, **base)


def _events(root):
    path = os.path.join(root, "serve_events.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def _verdict(root, rid):
    with open(os.path.join(root, "requests", rid, "verdict.json")) as f:
        return json.load(f)


def _journal_verifies(root, require_complete=True):
    records, torn = Journal.replay(os.path.join(root, "journal.jsonl"))
    return verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        require_complete=require_complete,
    )


def _reference_field(srv, spec):
    """The request's answer computed OUTSIDE the serving machinery: the
    same ensemble engine, one member, one unbounded advance."""
    tpl = srv._template(spec)
    ens = EnsembleSolver(
        tpl["family"].solver_cls, tpl["cfg"],
        [RequestServer._member_overrides(spec)],
    )
    out = ens.advance_to(ens.initial_state(), [float(spec.t_end)])
    return np.asarray(out.u[0], dtype=np.float32)


# --------------------------------------------------------------------- #
# Specs, coalesce keys, the request queue + journal
# --------------------------------------------------------------------- #
def test_spec_roundtrip_and_validation():
    spec = _spec("r1", operands={"diffusivity": 0.5},
                 ic_params={"width": 0.1}, priority=3, deadline_s=10.0)
    spec.validate()
    again = RequestSpec.from_json(json.loads(json.dumps(spec.to_json())))
    assert again == spec

    with pytest.raises(ValueError, match="request id"):
        _spec("../escape").validate()
    with pytest.raises(ValueError, match="dtype"):
        _spec("r2", dtype="float16").validate()
    with pytest.raises(ValueError, match="n must"):
        _spec("r3", n=[1]).validate()
    with pytest.raises(ValueError, match="lengths"):
        _spec("r4", lengths=[1.0]).validate()
    with pytest.raises(ValueError, match="t_end"):
        _spec("r5", t_end=float("nan")).validate()
    with pytest.raises(ValueError, match="deadline"):
        _spec("r6", deadline_s=0.0).validate()


def test_coalesce_key_groups_compatible_requests():
    a = _spec("a", operands={"diffusivity": 0.5}, t_end=0.1)
    b = _spec("b", operands={"diffusivity": 2.0}, t_end=0.7,
              ic_params={"width": 0.2}, priority=9)
    assert coalesce_key(a) == coalesce_key(b)  # member-varying only
    assert coalesce_key(a) != coalesce_key(_spec("c", n=[16, 16]))
    assert coalesce_key(a) != coalesce_key(_spec("d", dtype="float64"))
    assert coalesce_key(a) != coalesce_key(_spec("e", mesh="members=2"))
    assert coalesce_key(a) != coalesce_key(_spec("f", impl="pallas"))


def test_request_queue_journal_first_and_replay(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    q = RequestQueue(Journal(path, fsync=False))
    q.submit(_spec("r1", deadline_s=30.0))
    q.submit(_spec("r2"))
    q.transition("r1", "admitted")
    q.transition("r1", "batched", batch="b0", member=0)
    q.transition("r1", "running", attempt=1)
    q.transition("r1", "done", t=T_END, it=12, slices=3)
    q.transition("r2", "admitted")
    q.journal.close()

    q2, report = RequestQueue.replay(Journal(path, fsync=False))
    assert report["problems"] == []
    assert q2.requests["r1"].state == "done"
    assert q2.requests["r1"].slices == 3
    assert q2.requests["r1"].it == 12
    assert q2.requests["r2"].state == "admitted"
    # the admission wall clock survives replay (journal envelope wall),
    # so deadlines keep their original anchor across a restart
    assert q2.requests["r1"].admitted_wall is not None
    assert q2.requests["r2"].admitted_wall <= time.time()

    # one verifier, two state machines: the request journal linearizes
    # against the REQUEST transition table
    records, torn = Journal.replay(path)
    assert verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
    ) == []
    # ... and require_complete flags the non-terminal r2
    incomplete = verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        require_complete=True,
    )
    assert any("r2" in p for p in incomplete)


def test_illegal_request_transitions_rejected(tmp_path):
    q = RequestQueue(Journal(str(tmp_path / "j.jsonl"), fsync=False))
    q.submit(_spec("r1"))
    with pytest.raises(ValueError, match="illegal"):
        q.transition("r1", "running")
    q.transition("r1", "admitted")
    q.transition("r1", "batched")
    # failures must happen before batching or after running — a batched
    # record can only run or requeue
    with pytest.raises(ValueError, match="illegal"):
        q.transition("r1", "failed")


def test_deadline_aware_batch_order(tmp_path):
    q = RequestQueue(Journal(str(tmp_path / "j.jsonl"), fsync=False))
    now = time.time()
    q.submit(_spec("lazy"))
    q.submit(_spec("urgent", deadline_s=5.0))
    q.submit(_spec("vip", priority=5))
    for rid in ("lazy", "urgent", "vip"):
        q.transition(rid, "admitted", wall=now)
    order = [r.request_id for r in q.batchable()]
    # priority first, then earliest deadline, then FIFO
    assert order == ["vip", "urgent", "lazy"]


# --------------------------------------------------------------------- #
# Satellite 1: hardened spool ingest (requests AND jobs)
# --------------------------------------------------------------------- #
def test_request_spool_torn_entries_quarantined(tmp_path):
    root = str(tmp_path)
    submit_request_to_spool(root, _spec("good"))
    d = request_spool_dir(root)
    with open(os.path.join(d, "torn.json"), "w") as f:
        f.write('{"request_id": "to')  # truncated mid-write
    with open(os.path.join(d, "notdict.json"), "w") as f:
        f.write("[1, 2, 3]")
    with open(os.path.join(d, "badspec.json"), "w") as f:
        json.dump({"request_id": "badspec", "model": "diffusion",
                   "n": [1]}, f)  # fails validate()

    q = RequestQueue(Journal(str(tmp_path / "j.jsonl"), fsync=False))
    skips = []
    got = ingest_request_spool(root, q, on_skip=lambda n, e:
                               skips.append((n, e)))
    # the good request ingested; every bad one skipped, never fatal
    assert [r.request_id for r in got] == ["good"]
    assert sorted(n for n, _ in skips) == [
        "badspec.json", "notdict.json", "torn.json",
    ]
    # quarantined beside the spool so the evidence survives
    for name in ("torn.json", "notdict.json", "badspec.json"):
        assert os.path.exists(os.path.join(d, name + ".bad"))
        assert not os.path.exists(os.path.join(d, name))
    # ... and each skip is a named journal record
    records, _ = Journal.replay(q.journal.path)
    noted = [r["file"] for r in records
             if r.get("type") == "note" and r.get("note") == "spool_skip"]
    assert sorted(noted) == ["badspec.json", "notdict.json", "torn.json"]


def test_request_spool_dedupe_across_restart(tmp_path):
    """A server that died between journaling a submit and unlinking the
    spool file must not double-admit on restart."""
    root = str(tmp_path)
    jpath = str(tmp_path / "j.jsonl")
    submit_request_to_spool(root, _spec("r1"))
    q1 = RequestQueue(Journal(jpath, fsync=False))
    assert len(ingest_request_spool(root, q1)) == 1
    q1.journal.close()
    # crash re-creates the window: the spool file is back but the
    # journal already knows r1
    submit_request_to_spool(root, _spec("r1"))
    q2, _ = RequestQueue.replay(Journal(jpath, fsync=False))
    assert ingest_request_spool(root, q2) == []
    assert not os.path.exists(
        os.path.join(request_spool_dir(root), "r1.json")
    )
    assert list(q2.requests) == ["r1"]


def test_job_spool_torn_entries_quarantined(tmp_path):
    """The PR 14 job spool gets the same hardening: a torn mailbox
    entry is quarantined with a note record, never a daemon crash."""
    root = str(tmp_path)
    submit_to_spool(root, JobSpec(job_id="ok", argv=["run", "--n", "8"]))
    d = spool_dir(root)
    with open(os.path.join(d, "torn.json"), "w") as f:
        f.write('{"job_id": "to')
    with open(os.path.join(d, "notdict.json"), "w") as f:
        f.write('"a string"')

    q = JobQueue(Journal(str(tmp_path / "j.jsonl"), fsync=False))
    skips = []
    got = ingest_spool(root, q, on_skip=lambda n, e:
                       skips.append(n))
    assert [r.job_id for r in got] == ["ok"]
    assert sorted(skips) == ["notdict.json", "torn.json"]
    for name in ("torn.json", "notdict.json"):
        assert os.path.exists(os.path.join(d, name + ".bad"))
    records, _ = Journal.replay(q.journal.path)
    noted = [r["file"] for r in records
             if r.get("type") == "note" and r.get("note") == "spool_skip"]
    assert sorted(noted) == ["notdict.json", "torn.json"]


# --------------------------------------------------------------------- #
# Satellite 3: bounded per-member slices of the ensemble engine
# --------------------------------------------------------------------- #
def _slice_case(mesh=None, B=4):
    cfg = DiffusionConfig(grid=Grid.make(*N), dtype="float32",
                          impl="xla", ic="gaussian")
    members = [{"ic_params": (("width", 0.08 + 0.02 * i),)}
               for i in range(B)]
    es = EnsembleSolver(DiffusionSolver, cfg, members, mesh=mesh)
    est = es.initial_state()
    # staggered horizons: member i freezes ~4-5 steps after member i-1
    te = [T0 + 0.03 * (i + 1) for i in range(B)]
    return es, est, te


def _march_sliced(es, est, te, max_steps):
    prev_it = None
    for _ in range(200):
        est = es.advance_to(est, te, max_steps=max_steps)
        it = np.asarray(est.it).copy()
        if prev_it is not None and np.array_equal(it, prev_it):
            return est  # every member frozen at its own horizon
        prev_it = it
    raise AssertionError("members never froze")


def test_slice_boundaries_bit_exact_vs_unbounded():
    es, est, te = _slice_case()
    ref = es.advance_to(est, te)  # one unbounded advance
    out = _march_sliced(es, est, te, max_steps=3)
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(out.it), np.asarray(ref.it))
    t = np.asarray(out.t, dtype=np.float64)
    for i, t_end in enumerate(te):
        # f32 state time: the member freezes within one ULP of its
        # horizon (the server's frozen-lane fallback covers the same
        # borderline on the host side)
        assert t[i] >= t_end - 1e-6
    # staggered horizons froze at different step counts
    assert len(set(np.asarray(out.it).tolist())) > 1


def test_slice_boundary_freeze_is_stable():
    """Once a member reaches its horizon, further slices must not move
    it — finished lanes ride along bit-frozen while stragglers step."""
    es, est, te = _slice_case(B=2)
    out = _march_sliced(es, est, te, max_steps=4)
    again = es.advance_to(out, te, max_steps=4)
    np.testing.assert_array_equal(np.asarray(again.u), np.asarray(out.u))
    np.testing.assert_array_equal(np.asarray(again.it),
                                  np.asarray(out.it))


def test_slice_boundaries_member_sharded(devices):
    """The same slice-boundary contract on a member-sharded mesh: the
    per-member t_end vector rides the member sharding and per-member
    freeze survives the distributed dispatch."""
    mesh = make_mesh({"members": 2}, devices=devices[:2])
    es, est, te = _slice_case(mesh=mesh, B=4)
    ref = es.advance_to(est, te)
    out = _march_sliced(es, est, te, max_steps=3)
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(out.it),
                                  np.asarray(ref.it))
    t = np.asarray(out.t, dtype=np.float64)
    for i, t_end in enumerate(te):
        assert t[i] >= t_end - 1e-6


# --------------------------------------------------------------------- #
# The server, in process
# --------------------------------------------------------------------- #
def test_serve_coalesces_and_answers_bit_exactly(tmp_path):
    root = str(tmp_path / "root")
    specs = [
        _spec(f"r{i}", ic_params={"width": 0.08 + 0.02 * i},
              t_end=T0 + 0.02 * (i + 1))
        for i in range(3)
    ]
    for s in specs:
        submit_request_to_spool(root, s)
    srv = RequestServer(root, max_batch=4, slice_steps=4, fsync=False)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["reason"] == "idle"
        assert out["states"] == {"done": 3}
        evs = _events(root)
        batches = [e for e in evs if e["kind"] == "serve"
                   and e["name"] == "batch"]
        # ONE coalesced dispatch served all three requests
        assert batches and batches[0]["members"] == 3
        for s in specs:
            v = _verdict(root, s.request_id)
            assert v["status"] == "done"
            assert v["seconds"] is not None
            got = load_binary(
                os.path.join(root, "requests", s.request_id,
                             "result.bin"),
                tuple(N),
            )
            np.testing.assert_array_equal(got, _reference_field(srv, s))
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_serve_sheds_overload_with_retry_after(tmp_path):
    root = str(tmp_path / "root")
    for i in range(5):
        submit_request_to_spool(root, _spec(f"r{i}"))
    srv = RequestServer(root, max_batch=8, slice_steps=4,
                        queue_bound=2, retry_after_s=1.5, fsync=False)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        states = out["states"]
        assert states.get("shed", 0) >= 1
        assert states.get("done", 0) + states.get("shed", 0) == 5
        shed_evs = [e for e in _events(root) if e["kind"] == "serve"
                    and e["name"] == "shed"]
        assert shed_evs
        shed_rid = shed_evs[0]["job"]
        v = _verdict(root, shed_rid)
        assert v["status"] == "shed"
        assert v["reason"] == "queue_bound"
        assert v["retry_after_s"] == 1.5
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_bad_requests_fail_alone(tmp_path):
    root = str(tmp_path / "root")
    submit_request_to_spool(root, _spec("good"))
    submit_request_to_spool(root, _spec("nomodel", model="nope"))
    submit_request_to_spool(
        root, _spec("badoperand", operands={"vorticity": 1.0})
    )
    submit_request_to_spool(
        root, _spec("wrongmesh", mesh="members=4")
    )
    srv = RequestServer(root, max_batch=4, slice_steps=4, fsync=False)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 1, "failed": 3}
        assert _verdict(root, "good")["status"] == "done"
        assert "nope" in _verdict(root, "nomodel")["reason"]
        assert "vorticity" in _verdict(root, "badoperand")["reason"]
        assert "mesh" in _verdict(root, "wrongmesh")["reason"]
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_diverged_member_fails_alone_with_forensics(tmp_path):
    """One member poisoned through its operand diverges; ONLY that
    request fails (with crash.json forensics naming the member), the
    healthy one re-batches and completes."""
    root = str(tmp_path / "root")
    submit_request_to_spool(root, _spec("healthy"))
    submit_request_to_spool(
        root, _spec("poison", operands={"diffusivity": float("nan")})
    )
    srv = RequestServer(root, max_batch=4, slice_steps=4, fsync=False)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 1, "failed": 1}
        v = _verdict(root, "poison")
        assert v["status"] == "failed"
        assert "diverged" in v["reason"]
        with open(os.path.join(root, "requests", "poison",
                               "crash.json")) as f:
            forensics = json.load(f)
        assert forensics["type"] == "EnsembleMemberDivergedError"
        assert "member" in forensics
        div = [e for e in _events(root) if e["kind"] == "serve"
               and e["name"] == "divergence"]
        assert div and div[0]["jobs"] == ["poison"]
        assert _verdict(root, "healthy")["status"] == "done"
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_late_arrival_joins_at_slice_boundary(tmp_path):
    root = str(tmp_path / "root")
    submit_request_to_spool(root, _spec("early0"))
    submit_request_to_spool(root, _spec("early1"))
    srv = RequestServer(root, max_batch=4, slice_steps=2, fsync=False)
    try:
        # march a couple of slices, then a compatible request arrives
        for _ in range(3):
            srv.tick()
        assert srv._batch is not None
        submit_request_to_spool(root, _spec("late"))
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 3}
        evs = _events(root)
        joins = [e for e in evs if e["kind"] == "serve"
                 and e["name"] == "join"]
        assert joins, "late compatible arrival never triggered a join"
        # the join re-formed the batch: at least two batch events
        batches = [e for e in evs if e["kind"] == "serve"
                   and e["name"] == "batch"]
        assert len(batches) >= 2
        # and the joined answer is still the solver's answer
        got = load_binary(
            os.path.join(root, "requests", "late", "result.bin"),
            tuple(N),
        )
        np.testing.assert_array_equal(
            got, _reference_field(srv, _spec("late"))
        )
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_priority_preemption_at_slice_boundary(tmp_path):
    root = str(tmp_path / "root")
    # long low-priority work on one coalesce key ...
    submit_request_to_spool(root, _spec("slow0", t_end=5 * T_END))
    submit_request_to_spool(root, _spec("slow1", t_end=5 * T_END))
    srv = RequestServer(root, max_batch=2, slice_steps=2, fsync=False)
    try:
        for _ in range(3):
            srv.tick()
        assert srv._batch is not None
        # ... preempted by a strictly higher-priority incompatible key
        submit_request_to_spool(
            root, _spec("vip", n=[16, 16], priority=7)
        )
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 3}
        evs = _events(root)
        pre = [e for e in evs if e["kind"] == "serve"
               and e["name"] == "preempt"]
        assert pre and pre[0]["for_job"] == "vip"
        # the preempted members were parked with checkpoints and then
        # completed — requeued shows up in the journal trajectory
        records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
        requeues = [r for r in records if r.get("type") == "state"
                    and r.get("to") == "requeued"
                    and r.get("reason") == "preempted"]
        assert requeues
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_memory_admission_caps_and_fails(tmp_path):
    root = str(tmp_path / "root")
    per_member = int(math.prod(N)) * 4 * 8  # server's own estimate
    for i in range(3):
        submit_request_to_spool(root, _spec(f"r{i}"))
    # one member too big for the whole budget fails at admission
    submit_request_to_spool(root, _spec("huge", n=[256, 256]))
    srv = RequestServer(root, max_batch=8, slice_steps=4,
                        mem_budget_bytes=2 * per_member + 1,
                        fsync=False)
    try:
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 3, "failed": 1}
        assert "memory_budget" in _verdict(root, "huge")["reason"]
        # batch width was capped at 2 members by the budget: the third
        # compatible request was deferred, then served by a later batch
        evs = _events(root)
        defers = [e for e in evs if e["kind"] == "serve"
                  and e["name"] == "defer"
                  and e.get("reason") == "memory"]
        assert defers
        batches = [e for e in evs if e["kind"] == "serve"
                   and e["name"] == "batch"]
        assert all(b["members"] <= 2 for b in batches)
        assert len(batches) >= 2
        assert _journal_verifies(root) == []
    finally:
        srv.close()


def test_socket_submission_lands_in_spool(tmp_path):
    from multigpu_advectiondiffusion_tpu.service.server import (
        submit_request_over_socket,
    )

    root = str(tmp_path / "root")
    # AF_UNIX paths are ~108 chars max — keep the socket out of the
    # deep pytest tmp tree
    sock_dir = tempfile.mkdtemp(prefix="tpucfd_sock_")
    sock = os.path.join(sock_dir, "s")
    srv = RequestServer(root, max_batch=4, slice_steps=4,
                        socket_path=sock, fsync=False)
    try:
        submit_request_over_socket(sock, _spec("via-socket"))
        out = srv.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 1}
        assert _verdict(root, "via-socket")["status"] == "done"
    finally:
        srv.close()
        os.unlink(sock) if os.path.exists(sock) else None
        os.rmdir(sock_dir)


# --------------------------------------------------------------------- #
# In-process crash recovery (the real-SIGKILL half is below)
# --------------------------------------------------------------------- #
def test_recover_requeues_in_flight_and_completes(tmp_path):
    root = str(tmp_path / "root")
    specs = [_spec(f"r{i}", t_end=3 * T_END) for i in range(2)]
    for s in specs:
        submit_request_to_spool(root, s)
    srv1 = RequestServer(root, max_batch=4, slice_steps=2, fsync=False)
    for _ in range(3):
        srv1.tick()
    assert {r.state for r in srv1.queue.in_flight()} == {"running"}
    srv1.journal.close()  # abandon mid-batch: states stay running

    srv2 = RequestServer(root, max_batch=4, slice_steps=2, fsync=False)
    try:
        report = srv2.recover()
        assert report["requeued"] == 2
        assert report["failed"] == 0
        out = srv2.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 2}
        # every request answered EXACTLY once across both lives
        records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
        for s in specs:
            dones = [r for r in records if r.get("type") == "state"
                     and r.get("job") == s.request_id
                     and r.get("to") == "done"]
            assert len(dones) == 1
            got = load_binary(
                os.path.join(root, "requests", s.request_id,
                             "result.bin"),
                tuple(N),
            )
            np.testing.assert_array_equal(
                got, _reference_field(srv2, s),
                err_msg=f"{s.request_id}: checkpoint resume changed bits",
            )
        assert _journal_verifies(root) == []
    finally:
        srv2.close()


def test_recovery_exhausts_crash_retry_budget(tmp_path):
    root = str(tmp_path / "root")
    submit_request_to_spool(root, _spec("fragile", max_retries=0,
                                        t_end=3 * T_END))
    srv1 = RequestServer(root, max_batch=2, slice_steps=2, fsync=False)
    for _ in range(3):
        srv1.tick()
    assert srv1.queue.requests["fragile"].state == "running"
    srv1.journal.close()

    srv2 = RequestServer(root, max_batch=2, slice_steps=2, fsync=False)
    try:
        report = srv2.recover()
        assert report["failed"] == 1
        v = _verdict(root, "fragile")
        assert v["status"] == "failed"
        assert v["reason"] == "retries_exhausted"
        assert _journal_verifies(root) == []
    finally:
        srv2.close()


def test_corrupt_member_checkpoint_falls_back_to_ic(tmp_path):
    """A torn slice checkpoint must not wedge recovery: the member
    re-runs from its IC — bit-exact by the slicing invariance."""
    root = str(tmp_path / "root")
    spec = _spec("r0", t_end=3 * T_END)
    submit_request_to_spool(root, spec)
    srv1 = RequestServer(root, max_batch=2, slice_steps=2, fsync=False)
    for _ in range(3):
        srv1.tick()
    srv1.journal.close()
    ckpt = os.path.join(root, "requests", "r0", "member.ckpt")
    assert os.path.exists(ckpt)
    with open(ckpt, "r+b") as f:
        f.truncate(20)  # torn write

    srv2 = RequestServer(root, max_batch=2, slice_steps=2, fsync=False)
    try:
        out = srv2.serve(until_idle=True, poll_seconds=0.01)
        assert out["states"] == {"done": 1}
        got = load_binary(
            os.path.join(root, "requests", "r0", "result.bin"),
            tuple(N),
        )
        np.testing.assert_array_equal(got, _reference_field(srv2, spec))
    finally:
        srv2.close()


# --------------------------------------------------------------------- #
# CLI verbs
# --------------------------------------------------------------------- #
def test_cli_request_serve_verify_roundtrip(tmp_path, capsys):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli

    root = str(tmp_path / "root")
    cli(["request", "--root", root, "--request-id", "cli-r1",
         "--model", "diffusion", "--n", "12", "12",
         "--t-end", str(T_END), "--ic", "gaussian"])
    cli(["request", "--root", root, "--request-id", "cli-r2",
         "--model", "diffusion", "--n", "12", "12",
         "--t-end", str(T_END), "--ic", "gaussian",
         "--operand", "diffusivity=0.5", "--priority", "2"])
    cli(["serve-requests", "--root", root, "--until-idle",
         "--max-batch", "4", "--slice-steps", "4", "--poll", "0.01"])
    out = capsys.readouterr().out
    assert "done=2" in out
    assert _verdict(root, "cli-r1")["status"] == "done"
    # the --wait path polls the published verdict of an ALREADY-served
    # request (fresh id, already-terminal roots return immediately is
    # not a case — so spool a new one and serve again)
    cli(["request", "--root", root, "--request-id", "cli-r3",
         "--model", "diffusion", "--n", "12", "12",
         "--t-end", str(T_END), "--ic", "gaussian"])
    cli(["serve-requests", "--root", root, "--until-idle",
         "--max-batch", "4", "--slice-steps", "4", "--poll", "0.01"])
    capsys.readouterr()
    cli(["serve-requests", "--root", root, "--verify",
         "--require-complete"])
    out = capsys.readouterr().out
    assert "request journal linearizes" in out


def test_cli_verify_flags_incomplete_journal(tmp_path, capsys):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli

    root = str(tmp_path / "root")
    os.makedirs(root, exist_ok=True)
    q = RequestQueue(Journal(os.path.join(root, "journal.jsonl"),
                             fsync=False))
    q.submit(_spec("stuck"))
    q.transition("stuck", "admitted")
    q.journal.close()
    cli(["serve-requests", "--root", root, "--verify"])  # linearizes
    with pytest.raises(SystemExit) as exc:
        cli(["serve-requests", "--root", root, "--verify",
             "--require-complete"])
    assert exc.value.code == 1


# --------------------------------------------------------------------- #
# Chaos: a real SIGKILL mid-batch (satellite 2)
# --------------------------------------------------------------------- #
_SERVER_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main(["serve-requests", "--root", sys.argv[2], "--until-idle",
      "--max-batch", "4", "--slice-steps", "2", "--poll", "0.01"])
print("SERVE-WORKER-OK", flush=True)
'''

_CHAOS_T_END = 0.5  # ~60 steps at the 12x12 stability dt: many slices


def _chaos_specs():
    return [
        _spec(f"c{i}", t_end=_CHAOS_T_END,
              ic_params={"width": 0.08 + 0.02 * i})
        for i in range(4)
    ]


def _launch_server(tmp_path, tag, root):
    script = tmp_path / f"server_{tag}.py"
    script.write_text(_SERVER_WORKER)
    log = tmp_path / f"server_{tag}.log"
    handle = open(log, "w")
    proc = subprocess.Popen(
        [sys.executable, str(script), REPO, root],
        stdout=handle, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc, log, handle


def _run_to_completion(tmp_path, tag, root, timeout=240):
    proc, log, handle = _launch_server(tmp_path, tag, root)
    try:
        rc = proc.wait(timeout=timeout)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()
    assert rc == 0, f"server {tag} rc={rc}:\n{log.read_text()[-2000:]}"
    assert "SERVE-WORKER-OK" in log.read_text()


@pytest.mark.chaos
def test_sigkill_mid_batch_answers_every_request_once(tmp_path):
    """The acceptance chaos case: SIGKILL the serving daemon mid-batch,
    restart it, and every request is answered exactly once — journal
    linearizes under --require-complete discipline, and the bits match
    an uninterrupted server answering the same spool."""
    root = str(tmp_path / "killed")
    ref_root = str(tmp_path / "uninterrupted")
    for s in _chaos_specs():
        submit_request_to_spool(root, s)
        submit_request_to_spool(ref_root, s)

    # uninterrupted reference run (same subprocess environment, so the
    # bit-comparison is apples to apples)
    _run_to_completion(tmp_path, "ref", ref_root)

    proc, log, handle = _launch_server(tmp_path, "victim", root)
    try:
        slices_seen = faults.kill_server_mid_batch(proc, root,
                                                   timeout=180.0)
        assert slices_seen >= 1
        proc.wait(timeout=30)
        assert proc.returncode == -9
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        handle.close()

    # restart: recovery replays the journal and finishes the work
    _run_to_completion(tmp_path, "recovered", root)

    records, torn = Journal.replay(os.path.join(root, "journal.jsonl"))
    assert verify_records(
        records, torn=torn,
        allowed_transitions=ALLOWED_REQUEST_TRANSITIONS,
        terminal_states=REQUEST_TERMINAL_STATES,
        initial_state="received",
        require_complete=True,
    ) == []
    for s in _chaos_specs():
        dones = [r for r in records if r.get("type") == "state"
                 and r.get("job") == s.request_id
                 and r.get("to") == "done"]
        assert len(dones) == 1, (
            f"{s.request_id}: answered {len(dones)} times"
        )
        killed_bits = open(
            os.path.join(root, "requests", s.request_id, "result.bin"),
            "rb",
        ).read()
        ref_bits = open(
            os.path.join(ref_root, "requests", s.request_id,
                         "result.bin"),
            "rb",
        ).read()
        assert killed_bits == ref_bits, (
            f"{s.request_id}: SIGKILL recovery changed the answer"
        )
    # the restarted server journaled a crash-recovery requeue
    requeues = [r for r in records if r.get("type") == "state"
                and r.get("to") == "requeued"
                and r.get("reason") == "crash_recovery"]
    assert requeues


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_soak_two_rounds(tmp_path):
    """Soak: two kill/restart rounds against one root — attempts
    accumulate but stay within the default crash budget, and the final
    journal still linearizes complete."""
    root = str(tmp_path / "soak")
    for s in _chaos_specs():
        submit_request_to_spool(root, s)
    for round_no in range(2):
        proc, log, handle = _launch_server(tmp_path, f"soak{round_no}",
                                           root)
        try:
            faults.kill_server_mid_batch(proc, root, timeout=180.0)
            proc.wait(timeout=30)
        except TimeoutError:
            # the round finished before a slice could be killed — fine,
            # the exactly-once assertions below still hold
            pass
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            handle.close()
    _run_to_completion(tmp_path, "soak_final", root)
    assert _journal_verifies(root, require_complete=True) == []
    records, _ = Journal.replay(os.path.join(root, "journal.jsonl"))
    for s in _chaos_specs():
        dones = [r for r in records if r.get("type") == "state"
                 and r.get("job") == s.request_id
                 and r.get("to") == "done"]
        assert len(dones) == 1
