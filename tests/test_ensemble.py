"""Batched ensemble engine (ISSUE 9): member independence, loud
declines, member-attributed divergence, and the persistent AOT
executable cache.

Acceptance pins:

* a batched B=8 run is bit-exact (f32) against 8 looped single runs on
  the generic AND fused-stage rungs;
* one member injected to diverge names its index — the others'
  results are unaffected;
* the slab rung and device meshes decline batching loudly;
* a repeat request against a warm AOT cache loads the serialized
  executable (aot_cache:hit, compile seconds saved) instead of
  recompiling; corrupt/stale entries are misses, never crashes.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    EnsembleSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.models.state import (
    EnsembleState,
    SolverState,
)
from multigpu_advectiondiffusion_tpu.resilience.errors import (
    EnsembleMemberDivergedError,
)
from multigpu_advectiondiffusion_tpu.tuning import aot_cache


@pytest.fixture(autouse=True)
def _isolate_aot_cache(monkeypatch):
    """The AOT executable cache is opt-in and per-test: no ambient env
    enablement, fresh process state before and after."""
    monkeypatch.delenv(aot_cache.ENV_PATH, raising=False)
    saved = dict(aot_cache._state)
    aot_cache._state.update(dir=None, enabled=None)
    yield
    aot_cache._state.clear()
    aot_cache._state.update(saved)


def _diff_cfg(impl="xla", **kw):
    g = Grid.make(12, 10, 8, lengths=(1.2, 1.0, 0.8))
    return DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                           impl=impl, ic="gaussian", **kw)


def _members(B):
    return [
        {"ic_params": (("width", 0.1 + 0.02 * i),)} for i in range(B)
    ]


def _assert_bit_exact(es, B, iters):
    est = es.initial_state()
    out = es.run(est, iters)
    assert isinstance(out, EnsembleState) and out.members == B
    for i in range(B):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), iters)
        np.testing.assert_array_equal(
            np.asarray(out.u[i]), np.asarray(ref.u),
            err_msg=f"member {i} diverged from its looped single run",
        )
        assert float(out.t[i]) == float(ref.t)
    return out


# --------------------------------------------------------------------- #
# Member independence: batched == looped, bit-exact
# --------------------------------------------------------------------- #
def test_batched_b8_bit_exact_generic_diffusion():
    es = EnsembleSolver(DiffusionSolver, _diff_cfg("xla"), _members(8))
    _assert_bit_exact(es, 8, 3)
    assert es.engaged_path()["stepper"] == "ensemble-vmap[generic-xla]"


def test_batched_b8_bit_exact_fused_stage_diffusion():
    g = Grid.make(16, 12, 10, lengths=(1.6, 1.2, 1.0))
    cfg = DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                          impl="pallas_stage")
    es = EnsembleSolver(DiffusionSolver, cfg, 8)
    _assert_bit_exact(es, 8, 2)
    assert es.engaged_path()["stepper"] == "ensemble-vmap[fused-stage]"


def test_batched_b8_bit_exact_generic_burgers():
    cfg = BurgersConfig(grid=Grid.make(24, 8, 8, lengths=2.0), nu=1e-5,
                        adaptive_dt=False, dtype="float32", impl="xla")
    es = EnsembleSolver(BurgersSolver, cfg, _members(8))
    _assert_bit_exact(es, 8, 3)


@pytest.mark.slow
def test_batched_b8_ulp_exact_fused_stage_burgers():
    """Heavy variant (WENO5 per-stage Pallas kernels, interpret mode on
    CPU, vmapped B=8) — slow-marked so tier-1 stays inside its window;
    the fused-stage rung's BIT-exactness is tier-1-proven on diffusion
    above. WENO under a batched lowering reassociates at ulp level
    (measured max 1.2e-7 over 2 steps here) — the same equality grade
    the PR 4 deep-halo suite holds WENO5 to (diffusion bit-exact,
    WENO ulp; tests/test_comm_avoid.py)."""
    cfg = BurgersConfig(grid=Grid.make(16, 8, 8, lengths=2.0), nu=1e-5,
                        adaptive_dt=False, dtype="float32",
                        impl="pallas_stage")
    es = EnsembleSolver(BurgersSolver, cfg, _members(8))
    est = es.initial_state()
    out = es.run(est, 2)
    assert es.engaged_path()["stepper"] == "ensemble-vmap[fused-stage]"
    assert np.isfinite(np.asarray(out.u)).all()
    for i in range(8):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 2)
        np.testing.assert_allclose(
            np.asarray(out.u[i]), np.asarray(ref.u), rtol=0, atol=1e-6,
            err_msg=f"member {i} diverged past ulp from its single run",
        )
        assert float(out.t[i]) == float(ref.t)


# --------------------------------------------------------------------- #
# Member-varying scalars ride as batched operands
# --------------------------------------------------------------------- #
def test_member_varying_diffusivity_operand():
    Ks = [0.5, 1.0, 2.0]
    es = EnsembleSolver(DiffusionSolver, _diff_cfg("xla"),
                        [{"diffusivity": k} for k in Ks])
    est = es.initial_state()
    out = es.run(est, 3)
    assert es.engaged_path()["operands"] == ["diffusivity"]
    assert es.engaged_path()["stepper"] == "ensemble-vmap[generic-xla]"
    for i, K in enumerate(Ks):
        ms = es.member_solver(i)
        assert ms.cfg.diffusivity == K
        ref = ms.run(ms.initial_state(), 3)
        # the member's own stability dt moved with K — times match
        # exactly; the field matches to ulp (traced vs constant-folded
        # scalar multiply)
        assert float(out.t[i]) == pytest.approx(float(ref.t), abs=0.0)
        np.testing.assert_allclose(
            np.asarray(out.u[i]), np.asarray(ref.u), rtol=0, atol=1e-5,
        )


def test_member_varying_diffusivity_under_pallas_impl():
    """Regression (caught by the verify drive): a Pallas-flavored impl
    plus a member-varying K used to push the traced operand into the
    per-axis Pallas laplacian, which rejects captured traced constants.
    The operand path must route that op to XLA and say so."""
    g = Grid.make(16, 12, 10, lengths=(1.6, 1.2, 1.0))
    cfg = DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                          impl="pallas_stage")
    es = EnsembleSolver(DiffusionSolver, cfg,
                        [{"diffusivity": 0.5}, {"diffusivity": 2.0}])
    out = es.run(es.initial_state(), 2)
    assert es.engaged_path()["stepper"] == "ensemble-vmap[generic-xla]"
    for i, K in enumerate((0.5, 2.0)):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 2)
        assert float(out.t[i]) == float(ref.t)
        np.testing.assert_allclose(
            np.asarray(out.u[i]), np.asarray(ref.u), rtol=0, atol=1e-5,
        )


def test_member_varying_cfl_and_riemann_states_burgers():
    cfg = BurgersConfig(grid=Grid.make(64), dtype="float32",
                        adaptive_dt=False, ic="riemann", impl="xla")
    members = [
        {"cfl": 0.3, "ic_params": (("left", 2.0), ("right", 1.0))},
        {"cfl": 0.4, "ic_params": (("left", 1.5), ("right", 0.5))},
        {"cfl": 0.2, "ic_params": (("left", 1.0), ("right", -1.0))},
    ]
    es = EnsembleSolver(BurgersSolver, cfg, members)
    out = es.run(es.initial_state(), 5)
    for i in range(3):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 5)
        assert float(out.t[i]) == pytest.approx(float(ref.t), rel=1e-6)
        np.testing.assert_allclose(
            np.asarray(out.u[i]), np.asarray(ref.u), rtol=0, atol=1e-5,
        )
    rows = es.member_summaries(out)
    assert [r["member"] for r in rows] == [0, 1, 2]
    assert all("mass_drift" in r for r in rows)
    assert rows[1]["overrides"]["cfl"] == 0.4


def test_advance_to_ensemble_lands_every_member():
    Ks = [0.5, 1.0, 2.0]
    cfg = _diff_cfg("xla")
    es = EnsembleSolver(DiffusionSolver, cfg,
                        [{"diffusivity": k} for k in Ks])
    est = es.initial_state()
    t_end = float(est.t[0]) + 0.002
    out = es.advance_to(est, t_end)
    its = np.asarray(out.it)
    assert np.allclose(np.asarray(out.t), t_end, atol=1e-6)
    # smaller K -> bigger stable dt -> fewer steps; counts are
    # per-member (finished members freeze in the vmapped while loop)
    assert its[0] < its[2], its


# --------------------------------------------------------------------- #
# Loud declines + member-attributed divergence
# --------------------------------------------------------------------- #
def test_slab_pin_rides_the_b_folded_grid():
    """Since the mesh-scale round the slab pin is ADMITTED: uniform-
    physics ensembles fold B into the whole-run slab grid instead of
    being declined (tests/test_ensemble_mesh.py proves bit-exactness);
    member-varying operands still decline the pin loudly — the fold
    bakes uniform physics."""
    es = EnsembleSolver(DiffusionSolver, _diff_cfg("pallas_slab"), 4)
    out = es.run(es.initial_state(), 2)
    assert es.engaged_path()["stepper"] == (
        "ensemble-fold[fused-whole-run-slab]"
    )
    assert out.members == 4
    with pytest.raises(ValueError, match="uniform physics"):
        es2 = EnsembleSolver(
            DiffusionSolver, _diff_cfg("pallas_slab"),
            [{"diffusivity": 0.5}, {"diffusivity": 2.0}],
        )
        es2.run(es2.initial_state(), 1)


def test_spatial_only_mesh_declines_batching_loudly(devices):
    """A mesh WITHOUT a members axis still declines loudly: a purely
    spatial mesh shards one member's grid — ensembles compose with a
    mesh through the 'members' axis (tests/test_ensemble_mesh.py)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    mesh = make_mesh({"dz": 2}, devices=devices[:2])
    with pytest.raises(ValueError, match="members"):
        EnsembleSolver(DiffusionSolver, _diff_cfg("xla"), 4,
                       mesh=mesh, decomp=Decomposition.slab("dz"))


def test_unknown_member_override_rejected():
    with pytest.raises(ValueError, match="weno_order"):
        EnsembleSolver(BurgersSolver,
                       BurgersConfig(grid=Grid.make(32), impl="xla"),
                       [{"weno_order": 7}])


def test_diverging_member_names_index_others_unaffected():
    B = 6
    es = EnsembleSolver(DiffusionSolver, _diff_cfg("xla"), _members(B))
    est = es.initial_state()
    # poison member 3 in the evolving interior (wall cells would be
    # legitimately re-clamped by the Dirichlet post step)
    bad = est.u.at[3, 4, 5, 6].set(jnp.nan)
    est = EnsembleState(u=bad, t=est.t, it=est.it)
    out = es.run(est, 2)
    with pytest.raises(EnsembleMemberDivergedError) as exc:
        es.check_health(out)
    assert exc.value.members == [3]
    assert "member" in str(exc.value)
    # every healthy member is bit-exact against its looped single run
    for i in (0, 1, 2, 4, 5):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 2)
        np.testing.assert_array_equal(
            np.asarray(out.u[i]), np.asarray(ref.u),
            err_msg=f"healthy member {i} was poisoned by member 3",
        )


def test_ensemble_dispatch_event_schema(tmp_path):
    from multigpu_advectiondiffusion_tpu.telemetry import schema

    path = str(tmp_path / "ev.jsonl")
    es = EnsembleSolver(DiffusionSolver, _diff_cfg("xla"), 3)
    est = es.initial_state()
    with telemetry.capture(path):
        es.run(est, 2)
    evs = [json.loads(line) for line in open(path)]
    disp = [e for e in evs if e["kind"] == "ensemble"]
    assert disp and disp[0]["name"] == "dispatch"
    assert disp[0]["members"] == 3
    assert disp[0]["stepper"] == "ensemble-vmap[generic-xla]"
    for e in evs:
        assert schema.validate_event(e) == [], e


# --------------------------------------------------------------------- #
# Persistent AOT executable cache
# --------------------------------------------------------------------- #
def test_aot_cache_cold_store_warm_hit(tmp_path):
    aot_cache.configure(cache_dir=str(tmp_path / "aot"), enabled=True)
    cfg = _diff_cfg("xla")
    mpath = str(tmp_path / "cold.jsonl")
    es1 = EnsembleSolver(DiffusionSolver, cfg, 3)
    est = es1.initial_state()
    with telemetry.capture(mpath):
        cold = es1.run(est, 2)
    evs = [json.loads(line) for line in open(mpath)]
    stores = [e for e in evs if e["kind"] == "aot_cache"
              and e["name"] == "store"]
    assert stores and all(e["persisted"] for e in stores)
    assert not [e for e in evs if e["kind"] == "aot_cache"
                and e["name"] == "hit"]

    # a FRESH solver (new dispatch cache, same config) must load the
    # serialized executable instead of recompiling — and compute the
    # same answer
    wpath = str(tmp_path / "warm.jsonl")
    es2 = EnsembleSolver(DiffusionSolver, cfg, 3)
    with telemetry.capture(wpath):
        warm = es2.run(est, 2)
    evs = [json.loads(line) for line in open(wpath)]
    hits = [e for e in evs if e["kind"] == "aot_cache"
            and e["name"] == "hit"]
    assert hits, evs
    assert all(e["compile_seconds_saved"] > 0 for e in hits)
    assert not [e for e in evs if e["kind"] == "aot_cache"
                and e["name"] in ("miss", "store")]
    xla = [e for e in evs if e["kind"] == "xla" and e["name"] == "cost"]
    assert xla and all(e["aot"] == "hit" for e in xla)
    np.testing.assert_array_equal(np.asarray(cold.u), np.asarray(warm.u))


def test_aot_cache_key_separates_configs(tmp_path):
    """A (shape/dtype/impl/B)-different request never resolves to a
    stored executable — different keys, different entries."""
    aot_cache.configure(cache_dir=str(tmp_path / "aot"), enabled=True)
    cfg = _diff_cfg("xla")
    s1 = DiffusionSolver(cfg)
    s1.run(s1.initial_state(), 2)
    n_entries = len(os.listdir(str(tmp_path / "aot")))
    assert n_entries >= 1
    # same program key, different B -> distinct entries (the program
    # key carries B; the avals differ too)
    es = EnsembleSolver(DiffusionSolver, cfg, 2)
    es.run(es.initial_state(), 2)
    es2 = EnsembleSolver(DiffusionSolver, cfg, 4)
    es2.run(es2.initial_state(), 2)
    assert len(os.listdir(str(tmp_path / "aot"))) > n_entries + 1


def test_aot_cache_corrupt_and_stale_entries_are_misses(tmp_path):
    root = str(tmp_path / "aot")
    aot_cache.configure(cache_dir=root, enabled=True)
    cfg = _diff_cfg("xla")
    s1 = DiffusionSolver(cfg)
    st = s1.initial_state()
    s1.run(st, 2)
    entries = [os.path.join(root, n) for n in os.listdir(root)]
    assert entries
    # truncate every entry: the warm run must MISS (with a reason),
    # recompile, and still produce the right answer
    for p in entries:
        with open(p, "wb") as f:
            f.write(b"\x80corrupt")
    mpath = str(tmp_path / "ev.jsonl")
    s2 = DiffusionSolver(cfg)
    with telemetry.capture(mpath):
        out = s2.run(st, 2)
    evs = [json.loads(line) for line in open(mpath)]
    misses = [e for e in evs if e["kind"] == "aot_cache"
              and e["name"] == "miss"]
    assert misses and all(e["reason"] for e in misses)
    ref = DiffusionSolver(cfg).run(st, 2)
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))


def test_aot_cache_disabled_by_default(tmp_path):
    mpath = str(tmp_path / "ev.jsonl")
    s = DiffusionSolver(_diff_cfg("xla"))
    with telemetry.capture(mpath):
        s.run(s.initial_state(), 1)
    evs = [json.loads(line) for line in open(mpath)]
    assert not [e for e in evs if e["kind"] == "aot_cache"]
    assert not aot_cache.enabled()


# --------------------------------------------------------------------- #
# CLI surface
# --------------------------------------------------------------------- #
def test_cli_ensemble_sweep(tmp_path):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import main

    save = str(tmp_path / "out")
    mpath = str(tmp_path / "ev.jsonl")
    main([
        "diffusion3d", "--n", "12", "10", "8", "--iters", "3",
        "--ensemble", "3", "--sweep", "K=0.5:2.0",
        "--save", save, "--metrics", mpath,
    ])
    summary = json.load(open(os.path.join(save, "ensemble_summary.json")))
    assert summary["ensemble"] == 3
    assert len(summary["members"]) == 3
    ks = [m["overrides"]["diffusivity"] for m in summary["members"]]
    assert ks == pytest.approx([0.5, 1.25, 2.0])
    assert summary["mlups_members"] > 0
    assert summary["engaged"]["stepper"].startswith("ensemble-vmap")
    assert os.path.exists(os.path.join(save, "ensemble_result.bin"))
    evs = [json.loads(line) for line in open(mpath)]
    assert [e for e in evs if e["kind"] == "ensemble"]


def test_cli_ensemble_rejects_single_run_supervision(tmp_path):
    from multigpu_advectiondiffusion_tpu.cli.__main__ import main

    with pytest.raises(ValueError, match="checkpoint-every"):
        main([
            "diffusion3d", "--n", "12", "10", "8", "--iters", "2",
            "--ensemble", "2", "--checkpoint-every", "1",
            "--save", str(tmp_path),
        ])


def test_tuner_key_carries_ensemble_dimension(devices):
    """Satellite: a B=64 tuning decision can never be served to a B=1
    run — the ensemble member count is a first-class key dimension."""
    from multigpu_advectiondiffusion_tpu import tuning

    cfg = dataclasses.replace(_diff_cfg("xla"), impl="auto")
    k1 = tuning.make_key(DiffusionSolver, cfg, None, None, "cpu")
    k1b = tuning.make_key(DiffusionSolver, cfg, None, None, "cpu",
                          ensemble=1)
    k64 = tuning.make_key(DiffusionSolver, cfg, None, None, "cpu",
                          ensemble=64)
    assert k1 == k1b
    assert k64 != k1
    assert "ens=64" in k64 and "ens=1" in k1
