"""Engaged-path regression guard for the benchmark configs.

Every bench row promises a rung of the stepper ladder; a refactor that
silently drops a config to generic-xla/per-axis-pallas would otherwise
just publish a slow rate. bench.py enforces this at run time (the
engagement guard fails the run); this test enforces it at suite time —
WITHOUT timing anything, just by building each row's solver and asking
``engaged_path``.
"""

import importlib.util
import os

from jax.experimental import enable_x64

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_module():
    spec = importlib.util.spec_from_file_location(
        "bench_artifact", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_rows_engage_expected_steppers():
    """Each bench.py row's solver must engage a stepper from its
    expected set (CPU grids here; the TPU grids may legitimately sit on
    the other member of a {slab, stage} pair, never below it)."""
    bench = _bench_module()
    rows = bench._cases(on_tpu=False)
    assert len(rows) >= 13
    seen = {}
    for metric, make_solver, mode, work, baseline, expect in rows:
        with enable_x64(metric.endswith("_f64_mlups")):
            solver = make_solver()
            engaged = solver.engaged_path(
                "t_end" if mode == "t_end" else "iters"
            )
        assert engaged["stepper"] in expect, (
            metric, engaged["stepper"], engaged["fallback"]
        )
        # every row publishes its exchange cadence (ISSUE 4: bench rows
        # gain steps_per_exchange + tuner-provenance fields); the
        # single-chip pinned rows run the per-step cadence untuned
        assert engaged.get("steps_per_exchange") == 1, metric
        assert engaged.get("tuned") is None, metric
        seen[metric] = engaged["stepper"]
    # the slab-run round's acceptance rows: the 3-D headline Burgers
    # config and the f64 diffusion row must ride a fused path on the
    # CPU grids — specifically the new slab whole-run stepper
    assert seen["burgers3d_mlups"] == "fused-whole-run-slab"
    assert seen["diffusion3d_f64_mlups"] == "fused-whole-run-slab"
    # the pinned explicit rungs stay pinned
    assert seen["burgers3d_axis_mlups"] == "per-axis-pallas"


def test_bench_matrix_cases_report_engaged():
    """bench/matrix.py rows carry the engaged stepper in the artifact;
    the fused-impl cases must sit on the fused ladder (CPU-quick
    grids), and the f64 diffusion case must no longer report
    generic-xla."""
    from multigpu_advectiondiffusion_tpu.bench.matrix import (
        CASES,
        build_solver,
        resolve_impl,
    )

    for case in CASES:
        dtype = case.dtype
        grid_xyz = tuple(
            max(16, g // case.quick_scale) for g in case.grid_xyz
        )
        with enable_x64(dtype == "float64"):
            solver = build_solver(case, dtype, grid_xyz, None)
            engaged = solver.engaged_path()["stepper"]
        impl = resolve_impl(case, dtype)
        if impl == "pallas":
            assert engaged.startswith("fused-"), (case.name, engaged)
        elif impl == "pallas_axis":
            assert engaged == "per-axis-pallas", (case.name, engaged)
        if case.name == "diffusion3d_multigpu_f64":
            assert engaged != "generic-xla", engaged


def test_matrix_multichip_rows_route_through_auto():
    """With a --mesh spec the f32 pallas cases dispatch through the
    measured tuner (impl='auto'); single-chip and explicitly pinned
    rows are untouched (ISSUE 4 satellite)."""
    from multigpu_advectiondiffusion_tpu.bench.matrix import (
        CASES,
        resolve_impl,
    )

    by_name = {c.name: c for c in CASES}
    b3 = by_name["burgers3d_multigpu"]
    assert resolve_impl(b3, "float32", "dz=2") == "auto"
    assert resolve_impl(b3, "float32", None) == "pallas"
    assert resolve_impl(b3, "float32") == "pallas"  # legacy signature
    assert resolve_impl(
        by_name["burgers3d_512_axis"], "float32", "dz=2"
    ) == "pallas_axis"
    assert resolve_impl(
        by_name["diffusion3d_multigpu_f64"], "float64", "dz=2"
    ) == "pallas"


def test_scaling_configs_use_measured_dispatch():
    """The strong-scaling rows (the only standing multichip bench
    surface) dispatch through impl='auto' so a real multichip session
    tunes rung + steps_per_exchange instead of guessing."""
    from multigpu_advectiondiffusion_tpu.bench.scaling import _configs

    for cfg, _, _ in _configs(on_tpu=False).values():
        assert cfg.impl == "auto", cfg


def test_bench_ensemble_rows_engage_vmapped_steppers():
    """The ensemble_* rows' batched dispatch must ride the promised
    vmapped inner rung (ISSUE 9) — at B=2/1 iter so no timing is paid,
    just the dispatch record."""
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )

    bench = _bench_module()
    fams = bench._ensemble_cases(on_tpu=False)
    assert len(fams) >= 3
    for family, make_case, expect in fams:
        solver_cls, cfg, _iters, member_fn = make_case()
        es = EnsembleSolver(solver_cls, cfg,
                            [member_fn(i) for i in range(2)])
        es.run(es.initial_state(), 1)
        engaged = es.engaged_path()
        assert engaged["stepper"] in expect, (family, engaged)
        assert engaged["ensemble"] == 2, family
