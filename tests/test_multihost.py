"""Multi-host (DCN) layer tests on the virtual 8-device CPU backend.

The reference's multi-node story is MPI process management
(``InitializeMPI``, ``MultiGPU/Diffusion3d_Baseline/Tools.c:228-242``;
``MPIDeviceCheck``/``AssignDevices``, ``Util.cu:43-74``) and is untestable
without a cluster. The TPU-native layer (``parallel/multihost.py``) is
validated here without one: hybrid-mesh construction (DCN-outermost axis
ordering, clear failures on impossible topologies) in-process, and the
``jax.distributed`` runtime bring-up as a ``num_processes=1`` smoke in a
subprocess (so this process's backend stays pristine).
"""

import os
import socket
import subprocess
import sys

import pytest

import jax

from multigpu_advectiondiffusion_tpu.parallel import multihost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_hybrid_mesh_no_dcn_axis_uses_all_devices(devices):
    """dcn extent 1: plain mesh over all devices, ici axes innermost."""
    mesh = multihost.hybrid_mesh({"dz_ici": 8}, {})
    assert mesh.axis_names == ("dz_ici",)
    assert mesh.devices.shape == (8,)
    assert list(mesh.devices.ravel()) == list(jax.devices())


def test_hybrid_mesh_dcn_axis_is_outermost(devices):
    """Axis order is DCN axes then ICI axes — the outermost decomposition
    axis rides the slow links, matching the module's design contract."""
    mesh = multihost.hybrid_mesh({"a": 2, "b": 4}, {"d": 1})
    assert mesh.axis_names == ("d", "a", "b")
    assert mesh.devices.shape == (1, 2, 4)


def test_hybrid_mesh_runs_sharded_solve(devices):
    """A hybrid mesh is a plain Mesh: the standard sharded solver runs on
    it with z decomposed over the DCN-outermost compound axis, exactly as
    the module docstring prescribes for multi-host runs."""
    import numpy as np

    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )
    from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition

    mesh = multihost.hybrid_mesh({"dz_ici": 8}, {"dz_dcn": 1})
    # 3 cells per shard: bit-identity vs unsharded holds empirically for
    # shards >= 3 cells; degenerate 2-cell shards (= stencil halo) let XLA
    # reassociate the stencil sum differently (~1e-6 drift, still correct)
    grid = Grid.make(12, 12, 24, lengths=2.0)
    # decompose z over both mesh axes: dcn hop outermost, ici inside
    sharded = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32"),
        mesh=mesh,
        decomp=Decomposition.of({0: ("dz_dcn", "dz_ici")}),
    )
    plain = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float32"))
    a = sharded.run(sharded.initial_state(), 3)
    b = plain.run(plain.initial_state(), 3)
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))


def test_compound_axis_all_eight_devices_bit_identical(devices):
    """z split over a (2, 4) compound axis — 8 shards across two mesh
    axes — reproduces the unsharded solve bit-for-bit. This is the full
    multi-host layout (2 'hosts' x 4 'chips') on the virtual backend."""
    import numpy as np

    from multigpu_advectiondiffusion_tpu import (
        BurgersConfig,
        BurgersSolver,
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    mesh = make_mesh({"dz_dcn": 2, "dz_ici": 4})
    decomp = Decomposition.of({0: ("dz_dcn", "dz_ici")})
    grid = Grid.make(8, 8, 24, lengths=2.0)

    for cfg_cls, solver_cls, kw in (
        (DiffusionConfig, DiffusionSolver, {}),
        (BurgersConfig, BurgersSolver, {"nu": 1e-5}),
    ):
        sharded = solver_cls(
            cfg_cls(grid=grid, dtype="float32", **kw),
            mesh=mesh,
            decomp=decomp,
        )
        plain = solver_cls(cfg_cls(grid=grid, dtype="float32", **kw))
        a = sharded.run(sharded.initial_state(), 3)
        b = plain.run(plain.initial_state(), 3)
        if cfg_cls is DiffusionConfig:
            np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))
        else:
            # WENO: per-shape FMA contraction drifts a few ulps per step
            # (see tests/test_sharded.py::_WENO_ULPS for the rationale)
            bound = 32 * np.finfo(np.float32).eps
            assert np.abs(np.asarray(a.u) - np.asarray(b.u)).max() <= bound


def test_hybrid_mesh_device_count_mismatch_is_loud(devices):
    with pytest.raises(ValueError, match="devices"):
        multihost.hybrid_mesh({"dz_ici": 4}, {})


def test_hybrid_mesh_impossible_dcn_extent_is_loud(devices):
    """CPU devices carry no slice topology; a DCN extent > process count
    cannot be satisfied and must raise, not silently mis-place."""
    with pytest.raises(ValueError):
        multihost.hybrid_mesh({"dz_ici": 4}, {"dz_dcn": 2})


def test_process_local_devices_and_coordinator(devices):
    assert list(multihost.process_local_devices()) == list(jax.local_devices())
    assert multihost.is_coordinator()  # single-process: process_index 0


_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid, port = int(sys.argv[1]), sys.argv[2]

from multigpu_advectiondiffusion_tpu.parallel import multihost
multihost.initialize(coordinator_address=f"localhost:{port}",
                     num_processes=2, process_id=pid)
import numpy as np
from multigpu_advectiondiffusion_tpu import (
    BurgersConfig, BurgersSolver, DiffusionConfig, DiffusionSolver, Grid)
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition

assert jax.process_count() == 2 and len(jax.devices()) == 8
mesh = multihost.hybrid_mesh({"dz_ici": 4}, {"dz_dcn": 2})
assert mesh.devices.shape == (2, 4)
grid = Grid.make(12, 12, 24, lengths=2.0)
decomp = Decomposition.of({0: ("dz_dcn", "dz_ici")})
for name, cfg_cls, solver_cls, kw, tol in (
    ("diffusion", DiffusionConfig, DiffusionSolver, {}, 0.0),
    # WENO: per-shape FMA contraction drifts a few f32 ulps per step
    # (see test_sharded.py::_WENO_ULPS); adaptive dt adds a cross-
    # process gloo pmax to the mix
    ("burgers", BurgersConfig, BurgersSolver, {"nu": 1e-5},
     32 * np.finfo(np.float32).eps),
):
    cfg = cfg_cls(grid=grid, dtype="float32", **kw)
    solver = solver_cls(cfg, mesh=mesh, decomp=decomp)
    out = solver.run(solver.initial_state(), 4)
    ref_solver = solver_cls(cfg_cls(grid=grid, dtype="float32", **kw))
    ref = np.asarray(ref_solver.run(ref_solver.initial_state(), 4).u)
    worst = max(
        float(np.abs(np.asarray(sh.data) - ref[sh.index]).max())
        for sh in out.u.addressable_shards
    )
    assert worst <= tol, (name, worst, tol)
    print(f"proc {pid}: {name} ok (worst {worst:.2e})", flush=True)

# ---- the FUSED steppers across the real process boundary: ppermute
# ghost refresh, the split-overlap exch, the adaptive-dt pmax, and the
# 2-D per-stage kernels all riding gloo over the DCN axis — the
# reference's only deployment mode is its tuned kernels under mpirun
# (MultiGPU/*/run.sh) ----
ulp = 32 * np.finfo(np.float32).eps
fused_cases = (
    # serialized refresh + global wall offsets (diffusion is bitwise)
    ("diffusion3d-fused", DiffusionSolver,
     DiffusionConfig(grid=grid, dtype="float32", impl="pallas"),
     decomp, 0.0, False),
    # adaptive dt: the pmax wave-speed reduction crosses processes
    ("burgers3d-fused-adaptive", BurgersSolver,
     BurgersConfig(grid=grid, dtype="float32", nu=1e-5, impl="pallas"),
     decomp, ulp, False),
    # split overlap: the exchanged z-slab operands cross the DCN axis
    # while interior stage kernels run (lz=9 -> bz=3, n_bz=3)
    ("burgers3d-fused-split", BurgersSolver,
     BurgersConfig(grid=Grid.make(8, 8, 72, lengths=2.0),
                   dtype="float32", nu=1e-5, adaptive_dt=False,
                   impl="pallas", overlap="split"),
     decomp, ulp, True),
    # 2-D per-stage whole-shard kernels (the 2-D MultiGPU baselines'
    # tuned-kernel-under-MPI configuration)
    ("burgers2d-fused", BurgersSolver,
     BurgersConfig(grid=Grid.make(24, 24, lengths=2.0),
                   dtype="float32", nu=1e-4, impl="pallas"),
     Decomposition.of({0: ("dz_dcn", "dz_ici")}), ulp, False),
)
for name, solver_cls, cfg, dec, tol, want_split in fused_cases:
    solver = solver_cls(cfg, mesh=mesh, decomp=dec)
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded, (name, solver._fused_fallback)
    assert getattr(fused, "overlap_split", False) == want_split, name
    out = solver.run(solver.initial_state(), 3)
    assert ("fused_run", 3) in solver._cache, (name, "fused path not engaged")
    ref_solver = solver_cls(cfg)
    assert ref_solver._fused_stepper() is not None, name
    ref = np.asarray(ref_solver.run(ref_solver.initial_state(), 3).u)
    scale = max(float(np.abs(ref).max()), 1e-30)
    worst = max(
        float(np.abs(np.asarray(sh.data) - ref[sh.index]).max())
        for sh in out.u.addressable_shards
    )
    assert worst <= tol * scale or worst <= tol, (name, worst, tol)
    print(f"proc {pid}: {name} ok (worst {worst:.2e})", flush=True)

# ---- per-shard checkpoint across the process boundary: each process
# writes ONLY its addressable shards (+ manifest), then the state is
# reassembled onto the same mesh — no gather to one host at any point ----
from jax.experimental import multihost_utils
from multigpu_advectiondiffusion_tpu.utils import io as tio

ckdir = sys.argv[4]
cksolver = DiffusionSolver(
    DiffusionConfig(grid=grid, dtype="float32"), mesh=mesh, decomp=decomp)
ckstate = cksolver.run(cksolver.initial_state(), 2)
tio.save_checkpoint_sharded(ckdir, ckstate, grid=grid)
multihost_utils.sync_global_devices("ckpt-written")
back = tio.load_checkpoint_sharded(ckdir, sharding=cksolver.sharding())
assert float(back.t) == float(ckstate.t) and int(back.it) == int(ckstate.it)
want = {tuple(str(s) for s in sh.index): np.asarray(sh.data)
        for sh in ckstate.u.addressable_shards}
got = {tuple(str(s) for s in sh.index): np.asarray(sh.data)
       for sh in back.u.addressable_shards}
assert want.keys() == got.keys()
for k in want:
    assert np.array_equal(want[k], got[k]), k
print(f"proc {pid}: sharded-checkpoint ok", flush=True)
print(f"proc {pid}: MULTIPROC-OK", flush=True)
'''


def test_two_process_distributed_execution(tmp_path):
    """REAL multi-process execution — the capability the reference gets
    from mpirun (``MultiGPU/*/run.sh``): two OS processes, 4 virtual CPU
    devices each, joined by ``multihost.initialize``; ``hybrid_mesh``
    places the DCN axis on process granules; the unchanged sharded
    solvers run with ppermute halo hops (and the adaptive-dt pmax)
    crossing the process boundary over gloo — including the FUSED
    steppers (serialized ghost refresh, the split-overlap exch, and the
    2-D per-stage kernels), the reference's mpirun-plus-tuned-kernels
    deployment mode. Every process's local shards must match a
    locally-computed unsharded reference — bit-exactly for diffusion,
    to the documented WENO ulp bound for Burgers."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # worker output goes to files, not pipes: a full 64 KiB pipe would
    # stall that worker mid-collective, deadlocking its peer until the
    # timeout AND losing all diagnostics
    logs = [tmp_path / f"worker{i}.log" for i in range(2)]
    handles = [open(log, "w") for log in logs]
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(i), str(port), REPO,
                 str(tmp_path / "ckpt.ckptd")],
                stdout=handles[i],
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for i in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=300)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    finally:
        for h in handles:
            h.close()
    for i, (p, log) in enumerate(zip(procs, logs)):
        out = log.read_text()
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MULTIPROC-OK" in out


_CLI_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
pid, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[4]

from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main([
    "diffusion3d", "--n", "16", "16", "24", "--iters", "3",
    "--mesh", "dz_dcn=2,dz_ici=4", "--impl", "pallas",
    "--save", outdir, "--check-error",
    "--profile", outdir + "/trace",
    "--coordinator", f"localhost:{port}",
    "--num-processes", "2", "--process-id", str(pid),
])
print(f"proc {pid}: CLI-MULTIPROC-OK", flush=True)
'''


def test_two_process_cli_launch(tmp_path):
    """The mpirun analog end-to-end THROUGH THE CLI: two OS processes
    each run `diffusion3d --coordinator ... --mesh dz_dcn=2,dz_ici=4
    --impl pallas --save`, joining via jax.distributed; the compound
    mesh axis puts the DCN hop between process granules, the fused
    per-stage stepper runs shard-local, and file output happens once on
    the coordinator via a cross-process allgather. The reference's only
    deployment mode (`mpirun -np 2 ./Diffusion3d.run ...`,
    MultiGPU/*/run.sh) with restartable, validated artifacts on top."""
    import json

    import numpy as np

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    script = tmp_path / "cli_worker.py"
    script.write_text(_CLI_WORKER)
    outdir = tmp_path / "run"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    logs = [tmp_path / f"cli_worker{i}.log" for i in range(2)]
    handles = [open(log, "w") for log in logs]
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(i), str(port), REPO,
                 str(outdir)],
                stdout=handles[i],
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for i in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=300)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    finally:
        for h in handles:
            h.close()
    for i, (p, log) in enumerate(zip(procs, logs)):
        out = log.read_text()
        assert p.returncode == 0, f"cli proc {i} failed:\n{out[-3000:]}"
        assert "CLI-MULTIPROC-OK" in out

    # coordinator wrote the artifacts exactly once, from gathered shards
    from multigpu_advectiondiffusion_tpu.utils.io import load_binary

    u = load_binary(str(outdir / "result.bin"), (24, 16, 16))
    assert np.isfinite(u).all()
    summary = json.loads((outdir / "summary.json").read_text())
    assert summary["devices"] == 8
    assert summary["engaged"]["stepper"] == "fused-stage"
    # --check-error computed from allgathered shards on every process
    assert summary["error_l1"] is not None and summary["error_l1"] < 1.0
    # only the coordinator prints the summary block
    assert "kernel path" in logs[0].read_text()
    # --profile in a multi-process launch writes one trace dir PER
    # PROCESS (profile.sh's %q{OMPI_COMM_WORLD_RANK} per-rank naming,
    # MultiGPU/Diffusion3d_Baseline/profile.sh:2), each non-empty
    for rank in (0, 1):
        d = outdir / "trace" / f"rank{rank}"
        assert d.is_dir() and any(d.rglob("*")), f"missing trace for {rank}"
    assert "kernel path" not in logs[1].read_text()


def test_initialize_single_process_smoke():
    """``initialize()`` brings up jax.distributed with one process — the
    InitializeMPI analog — in a subprocess so this process's runtime is
    untouched."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    code = (
        "import os;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        f"import sys; sys.path.insert(0, {REPO!r});"
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from multigpu_advectiondiffusion_tpu.parallel import multihost;"
        f"multihost.initialize(coordinator_address='localhost:{port}',"
        " num_processes=1, process_id=0);"
        "assert jax.process_count() == 1, jax.process_count();"
        "assert multihost.is_coordinator();"
        "print('initialize-ok')"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stderr
    assert "initialize-ok" in res.stdout
