"""End-to-end smoke tests: every solver family constructs and steps."""

import jax.numpy as jnp
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)


@pytest.mark.parametrize("ndim", [1, 2, 3])
def test_diffusion_steps(ndim):
    sizes = {1: (33,), 2: (33, 17), 3: (17, 17, 9)}[ndim]
    grid = Grid.make(*sizes, lengths=10.0)
    solver = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float32"))
    state = solver.initial_state()
    out = solver.run(state, 5)
    assert out.u.shape == grid.shape
    assert bool(jnp.all(jnp.isfinite(out.u)))
    assert float(out.t) > float(state.t)
    assert int(out.it) == 5


@pytest.mark.parametrize("ndim", [1, 2, 3])
@pytest.mark.parametrize("order,variant", [(5, "js"), (5, "z"), (7, "js")])
def test_burgers_steps(ndim, order, variant):
    sizes = {1: (65,), 2: (33, 33), 3: (17, 17, 17)}[ndim]
    grid = Grid.make(*sizes, lengths=2.0)
    solver = BurgersSolver(
        BurgersConfig(
            grid=grid, weno_order=order, weno_variant=variant, dtype="float32"
        )
    )
    state = solver.initial_state()
    out = solver.run(state, 3)
    assert out.u.shape == grid.shape
    assert bool(jnp.all(jnp.isfinite(out.u)))
    # Gaussian IC in [0,1]: SSP + LF splitting should keep bounds (loosely)
    assert float(jnp.max(out.u)) <= 1.05
    assert float(jnp.min(out.u)) >= -0.05


def test_viscous_burgers():
    grid = Grid.make(65, lengths=2.0)
    solver = BurgersSolver(BurgersConfig(grid=grid, nu=1e-5, dtype="float32"))
    out = solver.run(solver.initial_state(), 3)
    assert bool(jnp.all(jnp.isfinite(out.u)))


def test_advance_to_lands_exactly():
    grid = Grid.make(33, lengths=10.0)
    solver = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float64"))
    state = solver.initial_state()  # t = t0 = 0.1
    out = solver.advance_to(state, 0.2)
    assert abs(float(out.t) - 0.2) < 1e-10


def test_advance_to_does_not_recompile_per_t_end():
    """t_end is a traced operand: a parameter sweep over end times must
    reuse ONE compiled program (the cache previously keyed on the float,
    compiling once per value)."""
    grid = Grid.make(33, lengths=10.0)
    solver = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float64"))
    state = solver.initial_state()
    for te in (0.15, 0.2, 0.3):
        out = solver.advance_to(state, te)
        assert abs(float(out.t) - te) < 1e-10
    adv_keys = [k for k in solver._cache if k == "adv" or (
        isinstance(k, tuple) and k and k[0] == "adv")]
    assert adv_keys == ["adv"]

    # same property for the MATLAB-exact accuracy loop
    for te in (0.15, 0.2):
        solver.advance_reference(state, te)
    ref_keys = [k for k in solver._cache if k == "advref" or (
        isinstance(k, tuple) and k and k[0] == "advref")]
    assert ref_keys == ["advref"]
