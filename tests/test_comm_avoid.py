"""Communication-avoiding deep-halo stepping (ISSUE 4 tentpole).

The sharded slab rung can exchange a ``k*G``-deep ghost zone once per
``k`` steps instead of ``G``-deep every step, recomputing the ghost
zone redundantly on shrinking windows in between (the cross-step
trapezoid). These tests pin:

* trajectory equality of k ∈ {1, 2, 4} against the per-step schedule
  (k=1) on 8-virtual-device sharded diffusion (bit-exact) and Burgers
  WENO5 (interpret-mode ulp bound), including a non-multiple iteration
  count (partial tail block);
* the split-overlap deep schedule (block-start exchange overlapped
  with the interior call) against the serialized one;
* dispatch validation: the knob is gated like the impl ladder —
  configs that cannot honor it fail loudly at construction/dispatch,
  never silently run the per-step cadence;
* engaged_path/telemetry reporting of the cadence actually in effect.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
)

_ULPS = 32 * np.finfo(np.float32).eps

# 6 steps = one full k=4 block + a 2-step partial tail at k=4, three
# full blocks at k=2 — every block-loop path executes
_ITERS = 6


def _zslab(cfg_cls, solver_cls, grid, devices, d, **kw):
    mesh = make_mesh({"dz": d}, devices=devices[:d])
    return solver_cls(cfg_cls(grid=grid, **kw), mesh=mesh,
                      decomp=Decomposition.slab("dz"))


@pytest.mark.parametrize("k", [1, 2, 4])
def test_deep_halo_diffusion_matches_per_step_8dev(devices, k):
    """k-step diffusion over all 8 virtual z-slabs is bit-identical to
    the per-step schedule: the extended windows run the same per-cell
    op sequence the neighbor would have run on its core rows."""
    # dz=8 -> local z = 24 = 4*G(6): every k candidate is servable
    grid = Grid.make(8, 8, 192, lengths=2.0)
    base = _zslab(DiffusionConfig, DiffusionSolver, grid, devices, 8,
                  dtype="float32", impl="pallas_slab")
    want = base.run(base.initial_state(), _ITERS)
    s = _zslab(DiffusionConfig, DiffusionSolver, grid, devices, 8,
               dtype="float32", impl="pallas_slab", steps_per_exchange=k)
    fused = s._fused_stepper()
    assert fused.steps_per_exchange == k
    assert fused.exchange_depth == k * fused.halo
    assert s.engaged_path()["steps_per_exchange"] == k
    out = s.run(s.initial_state(), _ITERS)
    assert float(jnp.max(jnp.abs(out.u - want.u))) == 0.0
    assert float(out.t) == float(want.t)
    assert int(out.it) == _ITERS


def test_deep_halo_burgers_weno5_matches_per_step_multidev(devices):
    """k-step Burgers WENO5 (viscous, fixed dt) over virtual z-slabs vs
    the per-step schedule, to the interpret-mode ulp bound. k ∈
    {1, 2, 4} in one test so the per-step baseline runs once (dz=4 of
    the 8-device fixture keeps the interpret cost tier-1-sized; the
    diffusion test above covers the full dz=8 decomposition)."""
    # dz=4 -> local z = 36 = 4*G(9). 4 iters: one exact k=4 block, two
    # k=2 blocks (the partial-tail-block path is pinned bit-exactly by
    # the diffusion test above, which runs _ITERS=6)
    iters = 4
    grid = Grid.make(8, 8, 144, lengths=2.0)
    base = _zslab(BurgersConfig, BurgersSolver, grid, devices, 4,
                  nu=1e-5, adaptive_dt=False, dtype="float32",
                  impl="pallas_slab")
    want = base.run(base.initial_state(), iters)
    d = np.asarray(want.u)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    for k in (1, 2, 4):
        s = _zslab(BurgersConfig, BurgersSolver, grid, devices, 4,
                   nu=1e-5, adaptive_dt=False, dtype="float32",
                   impl="pallas_slab", steps_per_exchange=k)
        assert s._fused_stepper().steps_per_exchange == k
        out = s.run(s.initial_state(), iters)
        a = np.asarray(out.u)
        assert float(np.max(np.abs(a - d))) <= _ULPS * scale, k
        assert float(out.t) == float(want.t)


def test_deep_halo_split_overlap_matches_serialized(devices):
    """The deep split-overlap schedule (block-start k*G exchange
    consumed by single-slab edge calls, interior call overlappable with
    the in-flight ppermute) vs the serialized deep refresh: diffusion,
    k=2, dz=2, incl. a partial tail block (5 = 2*2+1)."""
    grid = Grid.make(8, 8, 48, lengths=2.0)
    ser = _zslab(DiffusionConfig, DiffusionSolver, grid, devices, 2,
                 dtype="float32", impl="pallas_slab",
                 steps_per_exchange=2)
    want = ser.run(ser.initial_state(), 5)
    spl = _zslab(DiffusionConfig, DiffusionSolver, grid, devices, 2,
                 dtype="float32", impl="pallas_slab",
                 steps_per_exchange=2, overlap="split")
    fused = spl._fused_stepper()
    assert fused.overlap_split and fused.steps_per_exchange == 2
    assert spl.engaged_path()["overlap"] == "split"
    out = spl.run(spl.initial_state(), 5)
    a, d = np.asarray(out.u), np.asarray(want.u)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) <= _ULPS * scale


@pytest.mark.slow
def test_deep_halo_split_overlap_burgers_matches_serialized(devices):
    """The Burgers WENO5 deep split-overlap vs serialized equality —
    slow lane: tracing the edge/interior WENO5 call family costs ~40 s
    of interpret time, and tier-1 already pins the serialized deep
    Burgers trajectory, the diffusion deep split, and (dryrun) the
    Burgers deep-split execution."""
    bgrid = Grid.make(8, 8, 48, lengths=2.0)  # lz=24 > 2*G: split-able
    bser = _zslab(BurgersConfig, BurgersSolver, bgrid, devices, 2,
                  nu=1e-5, adaptive_dt=False, dtype="float32",
                  impl="pallas_slab", steps_per_exchange=2)
    bwant = bser.run(bser.initial_state(), 3)
    bspl = _zslab(BurgersConfig, BurgersSolver, bgrid, devices, 2,
                  nu=1e-5, adaptive_dt=False, dtype="float32",
                  impl="pallas_slab", steps_per_exchange=2,
                  overlap="split")
    assert bspl._fused_stepper().overlap_split
    bout = bspl.run(bspl.initial_state(), 3)
    a, d = np.asarray(bout.u), np.asarray(bwant.u)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) <= _ULPS * scale


def test_deep_halo_knob_validation(devices):
    """steps_per_exchange is gated like ops.IMPLS: bad values fail at
    config construction; configs that cannot host the schedule fail at
    solver construction or dispatch — never a silent per-step run."""
    grid = Grid.make(16, 16, 48, lengths=2.0)
    with pytest.raises(ValueError, match="steps_per_exchange"):
        DiffusionConfig(grid=grid, steps_per_exchange=0)
    with pytest.raises(ValueError, match="steps_per_exchange"):
        BurgersConfig(grid=grid, steps_per_exchange=-1)
    # unsharded: no exchanges to avoid
    with pytest.raises(ValueError, match="mesh"):
        DiffusionSolver(DiffusionConfig(
            grid=grid, dtype="float32", impl="pallas_slab",
            steps_per_exchange=2))
    # non-slab rungs cannot honor the cadence
    with pytest.raises(ValueError, match="slab rung"):
        DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_stage", steps_per_exchange=2),
            mesh=make_mesh({"dz": 2}, devices=devices[:2]),
            decomp=Decomposition.slab("dz"))
    # pencil meshes: z-slab only
    with pytest.raises(ValueError, match="z-slab"):
        DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", steps_per_exchange=2),
            mesh=make_mesh({"dz": 2, "dy": 2}, devices=devices[:4]),
            decomp=Decomposition.of({0: "dz", 1: "dy"}))
    # shard too thin to serve the k*G-deep exchange: dispatch-time error
    thin = _zslab(DiffusionConfig, DiffusionSolver,
                  Grid.make(16, 16, 16, lengths=2.0), devices, 2,
                  dtype="float32", impl="pallas_slab",
                  steps_per_exchange=4)
    with pytest.raises(ValueError, match="deep exchange"):
        thin.run(thin.initial_state(), 2)
    # adaptive dt rides the per-stage stepper: loud, not silent
    adaptive = _zslab(BurgersConfig, BurgersSolver,
                      Grid.make(16, 16, 72, lengths=2.0), devices, 2,
                      nu=1e-5, adaptive_dt=True, dtype="float32",
                      impl="pallas", steps_per_exchange=2)
    with pytest.raises(ValueError, match="adaptive"):
        adaptive.run(adaptive.initial_state(), 2)


def test_deep_halo_chunk_counts():
    from multigpu_advectiondiffusion_tpu.ops.pallas.stepper_base import (
        chunk_counts,
    )

    assert chunk_counts(6, 4) == (1, 2)
    assert chunk_counts(8, 4) == (2, 0)
    assert chunk_counts(3, 4) == (0, 3)
    assert chunk_counts(5, 1) == (5, 0)
    with pytest.raises(ValueError):
        chunk_counts(5, 0)
