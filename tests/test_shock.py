"""Shock-physics golden gate (VERDICT next-round #6).

The accuracy suite checks smooth-solution convergence orders; nothing
so far pinned the *nonlinear* physics. This gate does: a 1-D inviscid
Burgers Riemann problem (uL=2, uR=1, jump at x=0 — the `riemann` IC's
defaults) has the exact entropy solution of a single shock travelling
at s = (uL+uR)/2 = 1.5. After O(100) fixed-dt SSP-RK3 steps the
numerically-located shock must sit within ONE CELL of x = s*t, at WENO5
and WENO7, on the generic XLA path and on the fused Pallas steppers
(whole-run slab and per-stage — run pseudo-1-D on a 3-D grid, the only
world the fused kernels serve). A conservation bug, a flux-splitting
sign error, or a WENO-weight regression moves the shock speed and fails
this gate even when smooth-case OOA stays intact.

``tests/test_resilience.py`` reuses the same tolerance as the
"correct answer after rollback-retry" oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import BurgersConfig, BurgersSolver, Grid

UL, UR = 2.0, 1.0  # `riemann` IC defaults: shock speed (uL+uR)/2 = 1.5
SPEED = 0.5 * (UL + UR)
MID = SPEED  # u crosses (uL+uR)/2 inside the shock transition


def _shock_position(x: np.ndarray, u: np.ndarray) -> float:
    """x where u crosses the Rankine-Hugoniot midpoint, sub-cell via
    linear interpolation across the first downward crossing."""
    j = int(np.argmax(u < MID))
    assert j > 0, "no shock transition found in the profile"
    frac = (u[j - 1] - MID) / max(u[j - 1] - u[j], 1e-12)
    return float(x[j - 1] + frac * (x[j] - x[j - 1]))


def _assert_shock_within_one_cell(grid, out, x_axis: int, profile):
    x = np.asarray(grid.coords(x_axis, jnp.float32))
    x_shock = _shock_position(x, profile)
    exact = SPEED * float(out.t)  # jump starts at the domain midpoint 0
    dx = grid.spacing[x_axis]
    assert abs(x_shock - exact) <= dx, (
        f"shock at {x_shock:.5f}, exact {exact:.5f}: off by "
        f"{abs(x_shock - exact) / dx:.2f} cells"
    )


def _tv_diagnosed_run(solver, iters, sentinel_every=20):
    """Run under the supervisor with the fused diagnostic suite armed;
    returns the TV trajectory and asserts zero tolerance-rule
    violations (the TV-monotonicity rule is registered for the Burgers
    flux by BurgersSolver.diagnostics_spec)."""
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    out, report = supervise_run(
        solver, solver.initial_state(), iters=iters,
        sentinel_every=sentinel_every, diag_every=1,
    )
    diag = report.diagnostics
    assert "tv_monotone" in diag["rules"]
    assert diag["violations"] == [], diag["violations"]
    tvs = [p["tv"] for p in diag["trajectory"]]
    assert tvs, "no diagnostic trajectory recorded"
    # beyond the rule's tolerance check: the recorded trajectory itself
    # must stay bounded by the armed baseline (ENO shock physics)
    tv0 = diag["baseline"]["tv"]
    assert max(tvs) <= tv0 * 1.05 + 1e-9, (tv0, tvs)
    return out, tvs


@pytest.mark.parametrize("order", [5, 7])
def test_shock_tv_monotone_1d_generic(order):
    """TV-monotonicity diagnostic across the Riemann shock on the
    generic rung: the fused in-situ TV observable must stay bounded by
    the initial data's through 100 steps of shock propagation, at both
    WENO orders — spurious oscillation (a flux-split sign error, a
    broken smoothness weight) trips the rule even when the shock speed
    gate still passes."""
    grid = Grid.make(200, lengths=2.0)
    solver = BurgersSolver(
        BurgersConfig(grid=grid, ic="riemann", bc="edge",
                      weno_order=order, adaptive_dt=False, cfl=0.4,
                      dtype="float32")
    )
    out, tvs = _tv_diagnosed_run(solver, 100)
    _assert_shock_within_one_cell(grid, out, 0, np.asarray(out.u))


def test_shock_tv_monotone_3d_fused_slab(devices):
    """The same TV gate on the fused whole-run slab rung (pseudo-1-D
    3-D Riemann): the diagnostic probe samples between the slab rung's
    fused chunks, so a VMEM-pipeline defect that rang the profile
    trips the rule here."""
    del devices  # single-chip run; fixture only pins the 8-cpu env
    grid = Grid.make(128, 8, 8, lengths=[2.0, 2.0, 2.0])
    solver = BurgersSolver(
        BurgersConfig(grid=grid, ic="riemann", bc="edge",
                      weno_order=5, adaptive_dt=False, cfl=0.4,
                      dtype="float32", impl="pallas")
    )
    engaged = solver.engaged_path()["stepper"]
    assert engaged.startswith("fused"), (
        f"expected a fused rung, got {engaged} "
        f"({getattr(solver, '_fused_fallback', None)})"
    )
    out, tvs = _tv_diagnosed_run(solver, 60, sentinel_every=15)
    u = np.asarray(out.u)
    _assert_shock_within_one_cell(grid, out, 2, u[4, 4, :])


@pytest.mark.parametrize("order", [5, 7])
def test_shock_speed_1d_generic(order):
    grid = Grid.make(200, lengths=2.0)
    solver = BurgersSolver(
        BurgersConfig(grid=grid, ic="riemann", bc="edge",
                      weno_order=order, adaptive_dt=False, cfl=0.4,
                      dtype="float32")
    )
    state = solver.initial_state()
    out = solver.run(state, 100)  # O(100) steps, t = 100 * 0.4 * dx
    assert solver.engaged_path()["stepper"] == "generic-xla"
    _assert_shock_within_one_cell(grid, out, 0, np.asarray(out.u))


def test_shock_speed_3d_comm_avoiding_k4(devices):
    """The golden gate under the communication-avoiding schedule: the
    same Riemann shock marched 100 steps on a dz=2 z-slab mesh with
    steps_per_exchange=4 (one 36-deep exchange per 4 steps, redundant
    ghost recompute in between; 100 = 25 full blocks). Shock along x,
    sharded axis z uniform — a deep-schedule defect that let stale or
    mis-replicated ghost rows leak into the trapezoid would break the
    y/z uniformity or move the shock, failing the one-cell gate."""
    grid = Grid.make(200, 4, 72, lengths=[2.0, 2.0, 2.0])
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    solver = BurgersSolver(
        BurgersConfig(grid=grid, ic="riemann", bc="edge",
                      weno_order=5, adaptive_dt=False, cfl=0.4,
                      dtype="float32", impl="pallas_slab",
                      steps_per_exchange=4),
        mesh=make_mesh({"dz": 2}, devices=devices[:2]),
        decomp=Decomposition.slab("dz"),
    )
    fused = solver._fused_stepper()
    assert fused.steps_per_exchange == 4, "comm-avoiding schedule not engaged"
    assert fused.exchange_depth == 36
    out = solver.run(solver.initial_state(), 100)
    u = np.asarray(out.u)
    np.testing.assert_allclose(
        u, np.broadcast_to(u[:1, :1, :], u.shape), atol=1e-5
    )
    _assert_shock_within_one_cell(grid, out, 2, u[1, 1, :])


@pytest.mark.parametrize("order,impl", [(5, "pallas"), (7, "pallas_stage")])
def test_shock_speed_3d_fused(order, impl):
    """The fused rungs (whole-run slab via impl='pallas', per-stage via
    the 'pallas_stage' pin) on a pseudo-1-D 3-D grid: uniform in y/z,
    Riemann along x — the engaged stepper must be fused (a silent fall
    to the generic path would void the gate) and the shock speed exact
    to one cell. Both orders and both fused rungs are covered across
    the two parametrizations (kept to two so the gate stays cheap in
    tier-1)."""
    grid = Grid.make(200, 16, 16, lengths=[2.0, 2.0, 2.0])
    solver = BurgersSolver(
        BurgersConfig(grid=grid, ic="riemann", bc="edge",
                      weno_order=order, adaptive_dt=False, cfl=0.4,
                      dtype="float32", impl=impl)
    )
    engaged = solver.engaged_path()["stepper"]
    assert engaged.startswith("fused"), (
        f"expected a fused rung, got {engaged} "
        f"({getattr(solver, '_fused_fallback', None)})"
    )
    state = solver.initial_state()
    out = solver.run(state, 100)
    u = np.asarray(out.u)
    # y/z-uniformity must survive 100 fused steps (edge ghosts + no
    # transverse flux), so the centerline profile IS the 1-D solution
    np.testing.assert_allclose(
        u, np.broadcast_to(u[:1, :1, :], u.shape), atol=1e-5
    )
    _assert_shock_within_one_cell(grid, out, 2, u[8, 8, :])
