"""Trace analysis & live-monitoring layer (ISSUE 6).

Covers: JSONL stream loading (torn tails, rotation), cross-rank clock
alignment on sync anchors, span-forest reconstruction, phase breakdown,
Chrome/Perfetto ``trace_event`` export (schema-checked), the step-time
outlier watch + ``--progress`` renderer, the ``tpucfd-trace`` CLI, and
— the acceptance case — a REAL 2-process run's streams merged, aligned
and round-tripped through the exporter.
"""

from __future__ import annotations

import io
import json
import os
import socket
import subprocess
import sys
import time

import pytest

from multigpu_advectiondiffusion_tpu import telemetry
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.telemetry import analyze, export
from multigpu_advectiondiffusion_tpu.telemetry.live import (
    ProgressLine,
    StepTimeWatch,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_stream(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(path)


def _ev(t, kind, name, proc=0, **fields):
    return {"t": t, "proc": proc, "kind": kind, "name": name, **fields}


# --------------------------------------------------------------------- #
# Loading
# --------------------------------------------------------------------- #
def test_load_stream_skips_torn_tail(tmp_path):
    path = tmp_path / "ev.jsonl"
    _write_stream(path, [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(0.5, "physics", "probe", step=1, time=0.1),
    ])
    with open(path, "a") as f:
        f.write('{"t": 0.9, "proc": 0, "kind": "phys')  # torn mid-write
    s = analyze.load_stream(str(path))
    assert len(s.events) == 2
    assert s.skipped_lines == 1
    assert s.epoch == 1000.0


def test_load_stream_includes_rotated_segment(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    sink = telemetry.install(path, max_bytes=600)
    for i in range(30):
        sink.event("physics", "probe", step=i, time=0.1 * i)
    telemetry.uninstall(sink)
    assert os.path.exists(path + ".1"), "cap should have rotated"
    s = analyze.load_stream(path)
    # tail-only loading still has an epoch (sink:rotate carries one)
    assert s.epoch is not None
    # rotation must not reset the monotonic clock
    ts = [e["t"] for e in s.events]
    assert ts == sorted(ts)
    rot = [e for e in s.events if e["kind"] == "sink"]
    assert rot and rot[0]["name"] == "rotate"
    assert rot[0]["previous"].endswith(".1")
    assert rot[0]["rotated_bytes"] > 0


def test_load_streams_expands_directory(tmp_path):
    for i in range(2):
        _write_stream(tmp_path / f"ev_p{i}.jsonl", [
            _ev(0.0, "meta", "open", proc=i, schema=1,
                wall_time=1000.0 + i),
        ])
    streams = analyze.load_streams([str(tmp_path)])
    assert {s.proc for s in streams} == {0, 1}
    with pytest.raises(FileNotFoundError):
        analyze.load_streams([str(tmp_path / "empty_nowhere")])


# --------------------------------------------------------------------- #
# Clock alignment
# --------------------------------------------------------------------- #
def test_align_clocks_recovers_offset_from_anchors(tmp_path):
    # proc 0 opened its sink at wall 1000.0; proc 1 at wall 1000.40 —
    # but proc 1's wall clock also reads 0.05 s fast, so the epoch pass
    # alone leaves a residual skew only the anchors can remove.
    a = _write_stream(tmp_path / "p0.jsonl", [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(1.0, "resilience", "agree", tag="checkpoint", values=[8.0]),
        _ev(2.0, "resilience", "agree", tag="checkpoint", values=[16.0]),
        _ev(2.5, "sync", "barrier", tag="ckpt"),
    ])
    b = _write_stream(tmp_path / "p1.jsonl", [
        _ev(0.0, "meta", "open", proc=1, schema=1, wall_time=1000.45),
        _ev(0.50, "resilience", "agree", proc=1, tag="checkpoint",
            values=[8.0]),
        _ev(1.50, "resilience", "agree", proc=1, tag="checkpoint",
            values=[16.0]),
        _ev(2.00, "sync", "barrier", proc=1, tag="ckpt"),
    ])
    streams = analyze.load_streams([a, b])
    diag = analyze.align_clocks(streams)
    assert diag["reference_proc"] == 0
    assert diag["matched_anchors"]["proc1"] == 3
    s0, s1 = streams
    # after alignment the collective-completion events coincide
    assert abs(s0.gt(s0.events[1]) - s1.gt(s1.events[1])) < 1e-9
    assert abs(s0.gt(s0.events[3]) - s1.gt(s1.events[3])) < 1e-9
    # the correction found the 0.05 s wall-clock lie
    assert abs(diag["corrections_s"]["proc1"] - 0.05) < 1e-9
    assert diag["max_residual_s"] < 1e-9


def test_merged_events_interleave_on_global_time(tmp_path):
    a = _write_stream(tmp_path / "p0.jsonl", [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(3.0, "physics", "probe", step=2, time=0.2),
    ])
    b = _write_stream(tmp_path / "p1.jsonl", [
        _ev(0.0, "meta", "open", proc=1, schema=1, wall_time=1001.0),
        _ev(0.5, "physics", "probe", proc=1, step=1, time=0.1),
    ])
    streams = analyze.load_streams([a, b])
    analyze.align_clocks(streams)
    merged = analyze.merged_events(streams)
    # proc1's t=0.5 lands at gt=1.5, between proc0's 0.0 and 3.0
    kinds = [(e["proc"], e["kind"]) for e in merged]
    assert kinds == [(0, "meta"), (1, "meta"), (1, "physics"),
                     (0, "physics")]
    gts = [e["gt"] for e in merged]
    assert gts == sorted(gts)


# --------------------------------------------------------------------- #
# Span forest + phases
# --------------------------------------------------------------------- #
def _span_stream(tmp_path):
    return _write_stream(tmp_path / "spans.jsonl", [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(0.1, "span", "run_solver", phase="begin", id=1, parent=None,
            depth=0),
        # warm-up/compile call
        _ev(0.2, "span", "solver.run", phase="begin", id=2, parent=1,
            depth=1, stepper="generic-xla"),
        _ev(1.2, "span", "solver.run", phase="end", id=2, parent=1,
            depth=1, seconds=1.0),
        # two steady-state chunks
        _ev(1.3, "span", "solver.run", phase="begin", id=3, parent=1,
            depth=1, stepper="generic-xla"),
        _ev(1.5, "span", "solver.run", phase="end", id=3, parent=1,
            depth=1, seconds=0.2),
        _ev(1.6, "span", "solver.run", phase="begin", id=4, parent=1,
            depth=1, stepper="generic-xla"),
        _ev(1.8, "span", "solver.run", phase="end", id=4, parent=1,
            depth=1, seconds=0.2),
        _ev(1.85, "io", "checkpoint_write", path="x.ckpt", bytes=100,
            seconds=0.05),
        _ev(1.9, "progress", "chunk", step=10, steps_done=10,
            step_seconds=0.04),
        _ev(1.95, "resilience", "rollback", retry=1, step=10,
            rollback_to_it=5, action="dt -> 1e-3", norm=1.0,
            reason="non-finite"),
        _ev(2.0, "span", "run_solver", phase="end", id=1, parent=None,
            depth=0, seconds=1.9),
    ])


def test_span_forest_nesting(tmp_path):
    s = analyze.load_stream(_span_stream(tmp_path))
    roots = analyze.build_spans(s)
    assert len(roots) == 1 and roots[0].name == "run_solver"
    assert [c.name for c in roots[0].children] == ["solver.run"] * 3
    assert not roots[0].open


def test_phase_breakdown_accounts_compile_step_io_rollback(tmp_path):
    s = analyze.load_stream(_span_stream(tmp_path))
    p = analyze.phase_breakdown(s)
    assert p["total_s"] == pytest.approx(1.9, abs=1e-6)
    assert p["compile_s"] == pytest.approx(1.0, abs=1e-6)
    assert p["step_s"] == pytest.approx(0.4, abs=1e-6)
    assert p["checkpoint_io_s"] == pytest.approx(0.05, abs=1e-6)
    assert p["rollbacks"] == 1
    assert p["rollback_steps_reexecuted"] == 5
    # 5 re-executed steps at the progress-measured 0.04 s/step
    assert p["rollback_s_est"] == pytest.approx(0.2, abs=1e-6)
    assert p["open_spans"] == 0


def test_open_span_is_crash_evidence(tmp_path):
    path = _write_stream(tmp_path / "crash.jsonl", [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(0.1, "span", "run_solver", phase="begin", id=1, parent=None,
            depth=0),
        _ev(0.5, "crash", "RankFailureError", message="rank 1 died"),
    ])
    s = analyze.load_stream(path)
    assert analyze.phase_breakdown(s)["open_spans"] == 1
    obj = export.to_chrome_trace([s])
    assert export.validate_trace(obj) == []
    # the unclosed span exports as a lone B begin — visible evidence
    assert any(e["ph"] == "B" and e["name"] == "run_solver"
               for e in obj["traceEvents"])


# --------------------------------------------------------------------- #
# Perfetto export
# --------------------------------------------------------------------- #
def test_export_structure_and_validity(tmp_path):
    s = analyze.load_stream(_span_stream(tmp_path))
    obj = export.to_chrome_trace([s])
    assert export.validate_trace(obj) == []
    json.loads(json.dumps(obj))  # fully serializable
    evs = obj["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"run_solver", "solver.run"}
    # complete events carry microsecond ts/dur
    run = next(e for e in xs if e["name"] == "run_solver")
    assert run["dur"] == pytest.approx(1.9e6, rel=1e-6)
    assert any(e["ph"] == "M" and e["args"].get("name") == "rank0"
               for e in evs)
    inst = [e for e in evs if e["ph"] == "i"]
    assert any(e["name"] == "resilience:rollback" for e in inst)


def test_export_counters_as_counter_track(tmp_path):
    path = _write_stream(tmp_path / "c.jsonl", [
        _ev(0.0, "meta", "open", schema=1, wall_time=1000.0),
        _ev(0.1, "counter", "halo.bytes_per_execution", inc=512,
            total=512),
        _ev(0.2, "counter", "halo.bytes_per_execution", inc=512,
            total=1024),
    ])
    s = analyze.load_stream(path)
    obj = export.to_chrome_trace([s])
    cs = [e for e in obj["traceEvents"] if e["ph"] == "C"]
    assert [c["args"]["value"] for c in cs] == [512, 1024]


def test_validate_trace_rejects_malformed():
    assert export.validate_trace([]) != []
    assert export.validate_trace({"traceEvents": "nope"}) != []
    bad = {"traceEvents": [{"ph": "X", "name": "x", "pid": 0,
                            "ts": 1.0}]}  # missing dur
    assert any("dur" in p for p in export.validate_trace(bad))
    ok = {"traceEvents": [{"ph": "X", "name": "x", "pid": 0, "ts": 1.0,
                           "dur": 2.0}]}
    assert export.validate_trace(ok) == []


def test_write_chrome_trace_refuses_invalid(tmp_path, monkeypatch):
    s = analyze.load_stream(_span_stream(tmp_path))
    monkeypatch.setattr(export, "to_chrome_trace",
                        lambda streams: {"traceEvents": [{"ph": "?"}]})
    with pytest.raises(ValueError):
        export.write_chrome_trace(str(tmp_path / "t.json"), [s])


# --------------------------------------------------------------------- #
# Live layer: step-time watch + progress line
# --------------------------------------------------------------------- #
def test_step_time_watch_flags_stall_and_emits_event(tmp_path):
    path = str(tmp_path / "watch.jsonl")
    with telemetry.capture(path):
        w = StepTimeWatch(min_samples=4)
        for i in range(8):
            assert not w.observe(10, 0.1, step=10 * i)  # 10 ms/step
        assert w.observe(10, 1.0, step=80)  # 100 ms/step: a stall
        assert w.outliers == 1
        # the stall must not drag the baseline up
        assert w.median() == pytest.approx(0.01)
        summary = w.summary()
    assert summary["chunks"] == 9
    assert summary["outliers"] == 1
    assert sum(summary["counts"]) == 9
    evs = [json.loads(line) for line in open(path)]
    outs = [e for e in evs if e["kind"] == "perf" and
            e["name"] == "outlier"]
    assert len(outs) == 1
    assert outs[0]["step"] == 80
    assert outs[0]["step_seconds"] > outs[0]["threshold"]


def test_step_time_watch_needs_min_samples():
    w = StepTimeWatch(min_samples=8)
    assert w.threshold() is None
    for _ in range(3):
        w.observe(1, 1.0)
    # huge excursion before min_samples: recorded, never flagged
    assert not w.observe(1, 50.0)
    assert w.outliers == 0


def test_progress_line_renders_and_closes():
    out = io.StringIO()
    line = ProgressLine(label="diffusion3d", out=out, log_interval=0.0)
    line.update({"step": 100, "steps_done": 100, "steps_total": 400,
                 "rate_steps_per_s": 41.5, "mlups": 5123.0,
                 "eta_seconds": 7.2, "mass_drift": 1.2e-6,
                 "retries": 0, "outliers": 0})
    line.close()
    text = out.getvalue()
    assert "diffusion3d" in text
    assert "41.5 steps/s" in text
    assert "5123 MLUPS" in text
    assert "ETA 7s" in text
    assert "drift +1.20e-06" in text


# --------------------------------------------------------------------- #
# CLI integration: supervised run -> trace subcommand
# --------------------------------------------------------------------- #
def test_cli_trace_subcommand_reports_and_exports(tmp_path, devices,
                                                  capsys):
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "diffusion2d", "--n", "16", "12", "--iters", "6",
        "--mesh", "dy=2", "--sentinel-every", "2",
        "--checkpoint-every", "2", "--save", str(run),
        "--metrics", mpath,
    ])
    # the supervised run streamed progress events + a final histogram
    evs = [json.loads(line) for line in open(mpath)]
    prog = [e for e in evs if e["kind"] == "progress"]
    assert prog and all("step_seconds" in e for e in prog)
    assert any(e["kind"] == "perf" and e["name"] == "histogram"
               for e in evs)
    # ... and the step-time record landed in summary.json
    summary = json.load(open(run / "summary.json"))
    assert summary["resilience"]["perf"]["chunks"] >= 1

    capsys.readouterr()
    tpath = str(tmp_path / "trace.json")
    rpath = str(tmp_path / "report.json")
    cli_main(["trace", mpath, "--export", tpath, "--json",
              "--out", rpath])
    report = json.loads(capsys.readouterr().out)
    assert report == json.load(open(rpath))
    assert report["phases"][0]["step_s"] > 0
    rungs = report["rungs"]
    assert rungs and rungs[0]["run"] == "diffusion2d"
    assert rungs[0]["mlups"] > 0
    assert rungs[0]["roofline_pct"] is not None
    assert report["critical_path"]["chain"][0]["name"] == "run_solver"
    obj = json.load(open(tpath))
    assert export.validate_trace(obj) == []


def test_cli_progress_flag_needs_sentinel(tmp_path, devices):
    with pytest.raises(ValueError, match="sentinel"):
        cli_main([
            "diffusion2d", "--n", "16", "12", "--iters", "4",
            "--progress",
        ])


def test_cli_progress_flag_renders_status(tmp_path, devices, capsys):
    cli_main([
        "diffusion2d", "--n", "16", "12", "--iters", "4",
        "--sentinel-every", "2", "--progress",
    ])
    err = capsys.readouterr().err
    assert "steps/s" in err
    assert "ETA" in err


def test_cli_metrics_rotation_flag(tmp_path, devices):
    mpath = str(tmp_path / "rot.jsonl")
    cli_main([
        "diffusion2d", "--n", "16", "12", "--iters", "6",
        "--sentinel-every", "1", "--metrics", mpath,
        "--metrics-max-bytes", "2000",
    ])
    assert os.path.exists(mpath + ".1")
    assert os.path.getsize(mpath) < 4000
    # the merged view still loads (rotate event carries the epoch)
    s = analyze.load_stream(mpath)
    assert s.epoch is not None


# --------------------------------------------------------------------- #
# Acceptance: a REAL 2-process run's streams merge, align and export
# (launch plumbing pattern of tests/test_chaos.py)
# --------------------------------------------------------------------- #
_CLI_WORKER = r'''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main(json.loads(sys.argv[2]))
print("TRACE-WORKER-OK", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.chaos
def test_two_process_merged_trace(tmp_path):
    """Two real CLI ranks -> two JSONL streams -> merged trace: clocks
    align on the agree/barrier anchors, spans nest per rank, and the
    merged run exports as valid Chrome trace_event JSON."""
    port = _free_port()
    run = tmp_path / "run"
    run.mkdir()
    script = tmp_path / "worker.py"
    script.write_text(_CLI_WORKER)
    metrics = [str(tmp_path / f"events_p{i}.jsonl") for i in range(2)]
    logs = [tmp_path / f"w{i}.log" for i in range(2)]
    handles = [open(log, "w") for log in logs]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = []
    for i in range(2):
        args = [
            "diffusion3d", "--n", "16", "16", "24", "--iters", "40",
            "--mesh", "dz_dcn=2,dz_ici=4", "--save", str(run),
            "--coordinator", f"localhost:{port}",
            "--num-processes", "2", "--process-id", str(i),
            "--sentinel-every", "5", "--checkpoint-every", "10",
            "--checkpoint-sharded", "--metrics", metrics[i],
        ]
        procs.append(subprocess.Popen(
            [sys.executable, str(script), REPO, json.dumps(args)],
            stdout=handles[i], stderr=subprocess.STDOUT, text=True,
            env=env,
        ))
    try:
        deadline = time.time() + 240
        for i, p in enumerate(procs):
            rc = p.wait(timeout=max(1, deadline - time.time()))
            assert rc == 0, (
                f"worker {i} exited rc={rc}:\n"
                + logs[i].read_text()[-3000:]
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        for h in handles:
            h.close()

    streams = analyze.load_streams(metrics)
    assert {s.proc for s in streams} == {0, 1}
    diag = analyze.align_clocks(streams)
    # the coordinated checkpoints provided real agree anchors
    assert diag["matched_anchors"]["proc1"] >= 1
    assert diag["max_residual_s"] < 0.5
    s0 = next(s for s in streams if s.proc == 0)
    s1 = next(s for s in streams if s.proc == 1)
    a0 = [s0.gt(e) for e in s0.events
          if e["kind"] == "resilience" and e["name"] == "agree"]
    a1 = [s1.gt(e) for e in s1.events
          if e["kind"] == "resilience" and e["name"] == "agree"]
    assert a0 and len(a0) == len(a1)
    # aligned collective completions coincide across ranks
    assert all(abs(x - y) < 0.25 for x, y in zip(a0, a1))

    for s in streams:
        roots = analyze.build_spans(s)
        root = next(sp for sp in roots if sp.name == "run_solver")
        assert not root.open
        chunk_spans = [c for c in root.children
                       if c.name == "solver.run"]
        assert len(chunk_spans) >= 2  # warm-up + supervised chunks

    report = analyze.analyze(metrics)
    assert len(report.phases) == 2
    assert all(p["step_s"] > 0 for p in report.phases)
    assert report.critical_path["critical_rank"] in (0, 1)
    assert report.critical_path["end_skew_s"] < 60

    tpath = str(tmp_path / "trace.json")
    obj = export.write_chrome_trace(tpath, streams)
    assert export.validate_trace(obj) == []
    loaded = json.load(open(tpath))
    pids = {e["pid"] for e in loaded["traceEvents"]}
    assert pids == {0, 1}
    assert any(e.get("ph") == "X" and e["name"] == "solver.run"
               for e in loaded["traceEvents"])
