"""Distributed chaos suite (marker ``chaos``, CPU-only, tier-1 except
the soak).

The failure modes PR 2's single-process resilience layer cannot see are
injected here for real — OS signals against real processes, torn bytes
against real sharded checkpoints — and the full recovery loop proven:

* ``kill_rank`` (SIGKILL) mid-run: the survivor exits with the
  documented rank-failure code within the watchdog timeout (no MPI-style
  indefinite hang), and a restart with ``--resume auto`` — on the
  original 2-process mesh AND on a 1-process mesh (elastic resharded
  resume) — reproduces the uninterrupted run's final state bit-exactly;
* ``stall_rank`` (SIGSTOP): the pid stays alive, the heartbeat goes
  stale, the survivor still exits with the rank-failure code — the
  wedged-not-dead case that otherwise hangs forever inside gloo;
* ``torn_ckptd_write``: a ``.ckptd`` missing its COMMIT marker, missing
  a shard file, or carrying a manifest gap/overlap is never selected by
  ``--resume auto`` and the skip names the defect;
* ``sdc_at_step``: an injected duplicate-execution mismatch is detected
  at sentinel cadence, emitted as an ``sdc:detect`` event and recovered
  through the rollback path — bit-exactly, since SDC recovery keeps dt.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.parallel import multihost
from multigpu_advectiondiffusion_tpu.resilience import (
    EXIT_RANK_FAILURE,
    CoordinationError,
    RankFailureError,
    faults,
    find_latest_checkpoint,
    supervise_run,
)
from multigpu_advectiondiffusion_tpu.utils import io as io_utils
from multigpu_advectiondiffusion_tpu.utils.io import load_binary

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the 2-process chaos topology: z split over (2 processes) x (4 virtual
# devices); lz=24 -> 3 rows/shard, the documented bit-identity floor.
# ITERS sized so the post-kill runway (ITERS - CKPT_EVERY steps at
# ~25 ms/step over single-core gloo) dwarfs the kill latency while the
# 2-process restart stays tier-1-affordable.
GRID = ["--n", "16", "16", "24"]
SHAPE_ZYX = (24, 16, 16)
ITERS = 600
CKPT_EVERY = 25


# --------------------------------------------------------------------- #
# Two-process launch plumbing (pattern of tests/test_multihost.py:
# output to files, never pipes — a full pipe stalls a worker
# mid-collective and deadlocks its peer)
# --------------------------------------------------------------------- #
_CLI_WORKER = r'''
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
from multigpu_advectiondiffusion_tpu.cli.__main__ import main
main(json.loads(sys.argv[2]))
print("CHAOS-WORKER-OK", flush=True)
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_two(tmp_path, tag, cli_args_for):
    """Start two CLI worker subprocesses; returns (procs, logs, handles)."""
    port = _free_port()
    script = tmp_path / f"worker_{tag}.py"
    script.write_text(_CLI_WORKER)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    logs = [tmp_path / f"{tag}_w{i}.log" for i in range(2)]
    handles = [open(log, "w") for log in logs]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), REPO,
             json.dumps(cli_args_for(i, port))],
            stdout=handles[i], stderr=subprocess.STDOUT, text=True, env=env,
        )
        for i in range(2)
    ]
    return procs, logs, handles


def _cleanup(procs, handles):
    for p in procs:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=30)
    for h in handles:
        h.close()


def _wait_for_commit(run_dir, procs, logs, deadline_s=180):
    """Block until ``--resume auto`` would find a committed checkpoint
    under ``run_dir`` (i.e. the chunked loop is running)."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        picked = find_latest_checkpoint(str(run_dir), report=lambda m: None)
        if picked:
            return picked
        for i, p in enumerate(procs):
            if p.poll() is not None:
                pytest.fail(
                    f"worker {i} exited rc={p.returncode} before any "
                    "committed checkpoint:\n" + logs[i].read_text()[-3000:]
                )
        time.sleep(0.1)
    pytest.fail(f"no committed checkpoint within {deadline_s}s")


def _chaos_args(i, port, run_dir, iters=ITERS, extra=()):
    return [
        "diffusion3d", *GRID, "--iters", str(iters),
        "--mesh", "dz_dcn=2,dz_ici=4", "--save", str(run_dir),
        "--coordinator", f"localhost:{port}",
        "--num-processes", "2", "--process-id", str(i),
        *extra,
    ]


def _picked_iteration(path: str) -> int:
    stem = os.path.basename(path)[len("checkpoint_"):].rsplit(".", 1)[0]
    return int(stem)


# --------------------------------------------------------------------- #
# Acceptance: SIGKILL -> documented exit within the watchdog timeout ->
# restart (same mesh AND elastic reshard) -> bit-exact trajectory
# --------------------------------------------------------------------- #
def _kill_rank_cycle(tmp_path, tag, ref):
    """One kill -> abort -> both restarts cycle; returns the survivor's
    detection latency in seconds."""
    run = tmp_path / f"run_{tag}"
    run.mkdir()  # the --metrics sink opens before the run dir exists

    def argsf(i, port):
        return _chaos_args(
            i, port, run,
            extra=[
                "--checkpoint-every", str(CKPT_EVERY),
                "--checkpoint-sharded",
                "--sentinel-every", str(CKPT_EVERY),
                "--watchdog-timeout", "3",
                "--metrics", str(run / f"events_p{i}.jsonl"),
            ],
        )

    procs, logs, handles = _launch_two(tmp_path, f"kill_{tag}", argsf)
    try:
        _wait_for_commit(run, procs, logs)
        faults.kill_rank(procs[1])
        t_kill = time.time()
        # the survivor must NOT hang: documented exit code within the
        # watchdog window (generous slack for a loaded CI box)
        rc0 = procs[0].wait(timeout=90)
        detect_s = time.time() - t_kill
        procs[1].wait(timeout=30)
    finally:
        _cleanup(procs, handles)
    assert rc0 == EXIT_RANK_FAILURE, (
        f"survivor rc={rc0}:\n" + logs[0].read_text()[-3000:]
    )
    assert procs[1].returncode == -9  # SIGKILL took the victim

    # structured forensics: report file names the failed rank, and the
    # telemetry stream's tail carries the rank:failure event (the
    # crash-path flush satellite)
    report = json.loads((run / "rank_failure_p0.json").read_text())
    assert report["failed_rank"] == 1
    assert report["exit_code"] == EXIT_RANK_FAILURE
    events = [
        json.loads(line)
        for line in (run / "events_p0.jsonl").read_text().splitlines()
    ]
    kinds = {(e["kind"], e["name"]) for e in events}
    assert ("rank", "watchdog_armed") in kinds
    assert ("rank", "failure") in kinds

    # elastic resharded resume: 1 process, 8-way local mesh, reading
    # only the shard regions overlapping the NEW placement
    picked = find_latest_checkpoint(str(run))
    assert picked and picked.endswith(".ckptd")
    remaining = ITERS - _picked_iteration(picked)
    assert remaining > 0, "survivor finished before the kill landed"
    cli_main(["diffusion3d", *GRID, "--iters", str(remaining),
              "--mesh", "dz=8", "--save", str(run), "--resume", "auto"])
    out1 = load_binary(str(run / "result.bin"), SHAPE_ZYX)
    np.testing.assert_array_equal(out1, ref)

    # restart on the ORIGINAL 2-process topology from the same
    # checkpoint (the no-reshard recovery path)
    procs2, logs2, handles2 = _launch_two(
        tmp_path, f"restart_{tag}",
        lambda i, port: _chaos_args(
            i, port, run, iters=remaining, extra=["--resume", "auto"]
        ),
    )
    try:
        for i, p in enumerate(procs2):
            assert p.wait(timeout=240) == 0, (
                f"restart worker {i}:\n" + logs2[i].read_text()[-3000:]
            )
    finally:
        _cleanup(procs2, handles2)
    out2 = load_binary(str(run / "result.bin"), SHAPE_ZYX)
    np.testing.assert_array_equal(out2, ref)
    return detect_s


def _uninterrupted_reference(tmp_path):
    full = tmp_path / "full"
    cli_main(["diffusion3d", *GRID, "--iters", str(ITERS),
              "--save", str(full)])
    return load_binary(str(full / "result.bin"), SHAPE_ZYX)


def test_kill_rank_watchdog_exit_and_elastic_resume(tmp_path):
    ref = _uninterrupted_reference(tmp_path)
    detect_s = _kill_rank_cycle(tmp_path, "t1", ref)
    # detection bounded by the watchdog, not by a gloo/TCP timeout
    assert detect_s < 60


@pytest.mark.slow
def test_kill_restart_soak(tmp_path):
    """Multi-minute soak: the kill -> abort -> elastic-restart loop must
    hold up under repetition (out/soak_resilience.sh runs the whole
    chaos suite N times on top of this)."""
    ref = _uninterrupted_reference(tmp_path)
    for round_idx in range(3):
        _kill_rank_cycle(tmp_path, f"soak{round_idx}", ref)


def test_stall_rank_watchdog_exit(tmp_path):
    """SIGSTOP (not SIGKILL): the victim's pid stays alive so only the
    heartbeat-staleness path can catch it — the true hang case where
    gloo keeps its TCP connections open forever."""
    run = tmp_path / "run"

    def argsf(i, port):
        return _chaos_args(
            i, port, run,
            extra=[
                "--checkpoint-every", str(CKPT_EVERY),
                "--checkpoint-sharded",
                "--sentinel-every", str(CKPT_EVERY),
                "--watchdog-timeout", "2",
            ],
        )

    procs, logs, handles = _launch_two(tmp_path, "stall", argsf)
    resume = None
    try:
        _wait_for_commit(run, procs, logs)
        resume = faults.stall_rank(procs[1])
        t_stall = time.time()
        rc0 = procs[0].wait(timeout=90)
        detect_s = time.time() - t_stall
    finally:
        if resume is not None:
            resume()
        _cleanup(procs, handles)
    assert rc0 == EXIT_RANK_FAILURE, (
        f"survivor rc={rc0}:\n" + logs[0].read_text()[-3000:]
    )
    assert detect_s < 60
    report = json.loads((run / "rank_failure_p0.json").read_text())
    assert report["failed_rank"] == 1
    assert "stale" in report["reason"]


# --------------------------------------------------------------------- #
# Collective-schedule tracer cross-check (ISSUE 12): the measured
# per-rank collective sequence of a REAL 2-process run must be a
# linearization of the statically extracted schedule — the proof that
# the static verifier models the code that actually runs
# --------------------------------------------------------------------- #
def test_schedule_tracer_matches_static_schedule(tmp_path):
    from multigpu_advectiondiffusion_tpu.analysis import (
        collective_verify,
    )

    run = tmp_path / "run"
    run.mkdir()
    iters, every = 60, 20

    def argsf(i, port):
        return _chaos_args(
            i, port, run, iters=iters,
            extra=[
                "--checkpoint-every", str(every),
                "--checkpoint-sharded",
                "--sentinel-every", str(every),
                "--metrics", str(run / f"events_p{i}.jsonl"),
            ],
        )

    procs, logs, handles = _launch_two(tmp_path, "tracer", argsf)
    try:
        for i, p in enumerate(procs):
            assert p.wait(timeout=240) == 0, (
                f"worker {i}:\n" + logs[i].read_text()[-3000:]
            )
    finally:
        _cleanup(procs, handles)

    streams = {}
    profiles = {}
    for i in range(2):
        events = [
            json.loads(line)
            for line in (run / f"events_p{i}.jsonl")
            .read_text().splitlines()
        ]
        streams[i] = collective_verify.collective_sequence(events)
        profiles[i] = collective_verify.halo_counter_profile(events)

    # the run actually rendezvoused: 3 sharded checkpoints = 3 full
    # begin/shards/commit barrier chains + 3 checkpoint agrees
    assert len(streams[0]) >= 12, streams[0]
    assert any(kind == "agree" and tag == "checkpoint"
               for kind, tag in streams[0])

    schedule = collective_verify.static_schedule()
    problems = collective_verify.verify_trace(streams, schedule)
    assert problems == [], "\n".join(problems)
    # both ranks traced the same halo-exchange sites (the sharded z
    # exchange landed on every rank's compiled program identically)
    assert profiles[0], "no halo counters traced?"
    assert profiles[0] == profiles[1]

    # and the cross-check has teeth against this real stream: dropping
    # one rank's commit barrier (the hang case) is caught
    truncated = {
        0: streams[0],
        1: [x for x in streams[1]
            if not (x[0] == "barrier"
                    and str(x[1]).startswith("ckptd-commit"))],
    }
    assert collective_verify.verify_trace(truncated, schedule)


# --------------------------------------------------------------------- #
# Torn sharded checkpoints are never auto-selected
# --------------------------------------------------------------------- #
def _save_ckptd(devices, path, it=4):
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    mesh = Mesh(np.asarray(devices[:2]), ("dy",))
    sharding = NamedSharding(mesh, P("dy", None))
    u = jax.device_put(
        jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8), sharding
    )
    io_utils.save_checkpoint_sharded(
        path, SolverState(u=u, t=jnp.asarray(0.5), it=jnp.asarray(it))
    )


def test_torn_ckptd_variants_never_auto_selected(devices, tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    valid = str(d / "checkpoint_000010.ckptd")
    _save_ckptd(devices, valid, it=10)
    modes = (
        "uncommitted", "missing_shard", "manifest_gap", "manifest_overlap",
    )
    for k, mode in enumerate(modes):
        torn = str(d / f"checkpoint_{20 + k:06d}.ckptd")
        _save_ckptd(devices, torn, it=20 + k)
        faults.torn_ckptd_write(torn, mode)
        with pytest.raises(IOError):
            io_utils.verify_checkpoint(torn)
    reports = []
    picked = find_latest_checkpoint(str(d), report=reports.append)
    assert picked == valid
    assert len(reports) == len(modes)
    joined = "\n".join(reports)
    assert "COMMIT" in joined  # uncommitted named as such
    assert "missing" in joined  # absent shard file
    assert "gap" in joined  # manifest gap
    assert "overlap" in joined  # manifest overlap


def test_ckptd_commit_marker_written_last(devices, tmp_path):
    d = str(tmp_path / "c.ckptd")
    _save_ckptd(devices, d)
    assert os.path.exists(os.path.join(d, "COMMIT"))
    io_utils.verify_checkpoint(d)  # pristine passes
    faults.torn_ckptd_write(d, "uncommitted")
    with pytest.raises(IOError, match="COMMIT"):
        io_utils.verify_checkpoint(d)
    with pytest.raises(IOError, match="COMMIT"):
        io_utils.load_checkpoint(d)


def test_elastic_reshard_load(devices, tmp_path):
    """A .ckptd written on mesh A restores onto mesh B (different
    device count / axis split) and onto no mesh at all — each reader
    assembling only the regions its new placement needs."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    d = str(tmp_path / "c.ckptd")
    _save_ckptd(devices, d)  # written on a 2-way dy mesh
    full = io_utils.load_checkpoint(d)  # meshless
    want = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    np.testing.assert_array_equal(np.asarray(full.u), want)
    for n in (4, 8):
        sh = NamedSharding(
            Mesh(np.asarray(devices[:n]), ("dy",)), P("dy", None)
        )
        re = io_utils.load_checkpoint(d, sharding=sh)
        assert re.u.sharding.num_devices == n
        np.testing.assert_array_equal(np.asarray(re.u), want)
        assert float(re.t) == 0.5 and int(re.it) == 4


# --------------------------------------------------------------------- #
# SDC guard: inject -> sdc:detect event -> rollback -> bit-exact
# --------------------------------------------------------------------- #
def _diffusion2d():
    return DiffusionSolver(
        DiffusionConfig(
            grid=Grid.make(16, 12, lengths=4.0), dtype="float32"
        )
    )


def test_sdc_guard_detects_and_recovers_bit_exact(tmp_path):
    ref = _diffusion2d()
    ref_out = ref.run(ref.initial_state(), 12)

    solver = _diffusion2d()
    state = solver.initial_state()
    with telemetry.capture(str(tmp_path / "ev.jsonl")) as sink:
        with faults.sdc_at_step(solver, 4):
            out, report = supervise_run(
                solver, state, iters=12, sentinel_every=2, sdc_every=1,
                max_retries=2,
            )
        events = sink.tail(400)
    assert report.sdc_every == 1
    assert report.sdc_checks >= 2  # re-checked after the rollback
    assert report.sdc_detects == 1
    assert report.retries == 1
    assert report.events[0]["action"] == "recompute (dt unchanged)"
    assert "silent data corruption" in report.events[0]["reason"]
    kinds = [(e["kind"], e["name"]) for e in events]
    assert kinds.index(("sdc", "detect")) < kinds.index(
        ("resilience", "rollback")
    )
    # dt untouched -> the recovered trajectory IS the un-faulted one
    assert int(out.it) == 12
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref_out.u))


def test_sdc_persistent_corruption_exhausts_retries():
    solver = _diffusion2d()
    state = solver.initial_state()
    from multigpu_advectiondiffusion_tpu.resilience import SDCDetectedError

    with faults.sdc_at_step(solver, 2, once=False):
        with pytest.raises(SDCDetectedError):
            supervise_run(
                solver, state, iters=12, sentinel_every=2, sdc_every=1,
                max_retries=2,
            )


def test_sdc_needs_sentinel_cadence():
    solver = _diffusion2d()
    with pytest.raises(ValueError, match="sentinel"):
        supervise_run(
            solver, solver.initial_state(), iters=4, sdc_every=1,
        )


# --------------------------------------------------------------------- #
# Watchdog + timeout-wrapped collectives (in-process unit coverage)
# --------------------------------------------------------------------- #
def _dead_pid() -> int:
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


def test_watchdog_detects_dead_peer(tmp_path):
    failures = []
    wd = multihost.RankWatchdog(
        str(tmp_path), timeout_seconds=5.0, interval_seconds=0.05,
        rank=0, num_processes=2, on_failure=failures.append,
    )
    wd.start()
    try:
        multihost.write_heartbeat(str(tmp_path), 1, pid=_dead_pid())
        deadline = time.time() + 5
        while not failures and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert failures, "dead peer never detected"
    err = failures[0]
    assert isinstance(err, RankFailureError)
    assert err.rank == 1
    assert "dead" in err.reason
    assert wd.failure is err


def test_watchdog_detects_stale_heartbeat(tmp_path):
    failures = []
    wd = multihost.RankWatchdog(
        str(tmp_path), timeout_seconds=0.4, interval_seconds=0.05,
        rank=0, num_processes=2, on_failure=failures.append,
    )
    wd.start()
    try:
        # alive pid (our own) but a heartbeat that will never refresh
        multihost.write_heartbeat(str(tmp_path), 1, pid=os.getpid())
        deadline = time.time() + 5
        while not failures and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert failures and failures[0].rank == 1
    assert "stale" in failures[0].reason


def test_watchdog_ignores_previous_incarnation_records(tmp_path):
    """A restart reusing the save dir must not insta-fail on the dead
    previous run's heartbeat corpses — only records written after this
    watchdog started count as evidence."""
    multihost.write_heartbeat(
        str(tmp_path), 1, pid=_dead_pid(), wall=time.time() - 300.0
    )
    failures = []
    wd = multihost.RankWatchdog(
        str(tmp_path), timeout_seconds=10.0, interval_seconds=0.05,
        rank=0, num_processes=2, on_failure=failures.append,
    )
    wd.start()
    try:
        time.sleep(0.4)
        assert not failures
        # a fresh record from the (restarted) peer replaces the corpse
        multihost.write_heartbeat(str(tmp_path), 1, pid=os.getpid())
        time.sleep(0.3)
        assert not failures
    finally:
        wd.stop()


def test_collective_timeout_raises_rank_failure():
    with pytest.raises(RankFailureError, match="did not complete"):
        multihost.call_with_timeout(
            lambda: time.sleep(5.0), 0.2, "unit-collective"
        )
    # fast path: value passes through, exceptions re-raise
    assert multihost.call_with_timeout(lambda: 7, 0.5, "ok") == 7
    with pytest.raises(ZeroDivisionError):
        multihost.call_with_timeout(lambda: 1 // 0, 0.5, "err")


def test_agree_single_process_and_desync(monkeypatch):
    # single process: agreement is trivially the proposed vector
    np.testing.assert_array_equal(
        multihost.agree("t", [3.0, 4.0]), np.asarray([3.0, 4.0])
    )
    # forge a 2-rank world where the peers disagree
    from jax.experimental import multihost_utils

    monkeypatch.setattr(multihost.jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: np.stack([arr, arr + 1.0]),
    )
    with pytest.raises(CoordinationError, match="agreement"):
        multihost.agree("rollback", [3.0])
    # and one where they agree
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: np.stack([arr, arr]),
    )
    np.testing.assert_array_equal(
        multihost.agree("rollback", [3.0, 1.0]), np.asarray([3.0, 1.0])
    )


def test_watchdog_scope_classifies_generic_error(tmp_path):
    """A generic exception (gloo 'connection reset') raised while a
    peer is down must surface as the structured RankFailureError, with
    the forensics report written."""
    wd = multihost.RankWatchdog(
        str(tmp_path / "hb"), timeout_seconds=30.0, interval_seconds=0.05,
        rank=0, num_processes=2, on_failure=lambda e: None,
        report_dir=str(tmp_path),
    )
    with pytest.raises(RankFailureError) as ei:
        with multihost.watchdog_scope(wd):
            multihost.write_heartbeat(str(tmp_path / "hb"), 1,
                                      pid=_dead_pid())
            raise RuntimeError("connection reset by peer")
    assert ei.value.rank == 1
    assert multihost.current_watchdog() is None  # uninstalled on exit
    report = json.loads((tmp_path / "rank_failure_p0.json").read_text())
    assert report["failed_rank"] == 1


# --------------------------------------------------------------------- #
# Crash-safe scheduler (ISSUE 14): SIGKILL the daemon mid-queue ->
# restart -> journal replay completes every job bit-exact; priority
# preemption round-trips through exit 75 + elastic resharded resume
# --------------------------------------------------------------------- #
from multigpu_advectiondiffusion_tpu.service import (  # noqa: E402
    Journal,
    JobSpec,
    Scheduler,
    submit_to_spool,
)

# j1/j3 are identical (the warm-admission pair); j2 is the mid-queue
# victim — iters sized so the post-first-checkpoint runway (~2 s of
# chunked dispatches) dwarfs the test's kill-detection latency
_SJOB = ["diffusion2d", "--n", "24", "16", "--checkpoint-every", "500",
         "--iters", "50000"]
_SJOB_K = [*_SJOB, "--K", "0.7"]


def _launch_daemon(root, log_path, max_concurrent=1, devices=1):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else ""
        ),
    }
    fh = open(log_path, "a")
    proc = subprocess.Popen(
        [sys.executable, "-m", "multigpu_advectiondiffusion_tpu.cli",
         "serve", "--root", str(root), "--until-idle",
         "--max-concurrent", str(max_concurrent),
         "--devices", str(devices), "--poll", "0.1"],
        stdout=fh, stderr=subprocess.STDOUT, env=env,
    )
    return proc, fh


def _journal_records(root):
    records, _ = Journal.replay(os.path.join(str(root), "journal.jsonl"))
    return records


def _running_pid(root, job_id):
    pid = None
    for r in _journal_records(root):
        if (r.get("type") == "state" and r.get("job") == job_id
                and r.get("to") == "running"):
            pid = r.get("pid")
    return pid


def _sched_events(root):
    return [
        json.loads(line)
        for line in open(os.path.join(str(root), "sched_events.jsonl"))
        if line.strip()
    ]


def _kill_daemon_mid_job(tmp_path, root, victim, round_tag):
    """Start the daemon, wait for ``victim`` to be running with a
    committed checkpoint, SIGKILL the daemon, and prove the pdeathsig
    took the worker down too (so the restart must RESUME, not adopt)."""
    proc, fh = _launch_daemon(root, tmp_path / f"daemon_{round_tag}.log")
    victim_dir = os.path.join(str(root), "jobs", victim)
    try:
        deadline = time.time() + 240
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail(
                    f"daemon exited rc={proc.returncode} before the "
                    "kill window:\n"
                    + open(tmp_path / f"daemon_{round_tag}.log")
                    .read()[-3000:]
                )
            if (_running_pid(root, victim) is not None
                    and find_latest_checkpoint(
                        victim_dir, report=lambda m: None)):
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"{victim} never reached a committed checkpoint")
        pid = _running_pid(root, victim)
        faults.kill_rank(proc)  # SIGKILL: no cleanup, no final journal
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        fh.close()
    # PR_SET_PDEATHSIG: the in-flight worker dies with its daemon —
    # the restart exercises journal replay + --resume auto, never a
    # live-orphan adoption
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        pytest.fail(f"worker {pid} survived the daemon's death")
    assert not os.path.exists(os.path.join(victim_dir, "summary.json")), (
        "victim finished before the kill — no mid-run coverage"
    )


def test_scheduler_sigkill_midqueue_replay_bit_exact(tmp_path):
    root = tmp_path / "root"
    # uninterrupted references, one per distinct config
    refs = {}
    for tag, argv in (("a", _SJOB), ("b", _SJOB_K)):
        d = tmp_path / f"ref_{tag}"
        cli_main([*argv, "--save", str(d)])
        refs[tag] = (d / "result.bin").read_bytes()

    for jid, argv in (("j1", _SJOB), ("j2", _SJOB_K), ("j3", _SJOB)):
        submit_to_spool(str(root), JobSpec(job_id=jid, argv=list(argv)))

    _kill_daemon_mid_job(tmp_path, root, "j2", "t1")

    # restart: replay the journal, resume j2 from its checkpoint,
    # run j3 (warm — j1's identical request completed before the kill)
    proc2, fh2 = _launch_daemon(root, tmp_path / "daemon2.log")
    try:
        rc = proc2.wait(timeout=600)
    finally:
        if proc2.poll() is None:
            proc2.kill()
            proc2.wait(timeout=30)
        fh2.close()
    assert rc == 0, open(tmp_path / "daemon2.log").read()[-3000:]

    # the journal linearizes and every job is terminal
    assert cli_main(["serve", "--root", str(root), "--verify",
                     "--require-complete"]) is None

    # bit-exact vs the uninterrupted runs (f32 diffusion)
    for jid, tag in (("j1", "a"), ("j2", "b"), ("j3", "a")):
        got = (root / "jobs" / jid / "result.bin").read_bytes()
        assert got == refs[tag], f"{jid} diverged from its reference"

    # j1 completed before the kill and was NOT re-run on restart
    runs = [r for r in _journal_records(root)
            if r.get("type") == "state" and r.get("to") == "running"]
    assert len([r for r in runs if r["job"] == "j1"]) == 1
    assert len([r for r in runs if r["job"] == "j2"]) == 2

    evs = _sched_events(root)
    recover = [e for e in evs
               if e["kind"] == "sched" and e["name"] == "recover"][-1]
    assert recover["requeued"] >= 1
    # warm admission after the restart: the ledger replayed from the
    # journal, and j3's dispatches all came from the AOT cache
    admits = {e["job"]: e for e in evs
              if e["kind"] == "sched" and e["name"] == "admit"}
    assert admits["j3"]["warm"] is True
    j3_aot = [
        e["name"]
        for e in (json.loads(line) for line in open(
            root / "jobs" / "j3" / "events.jsonl") if line.strip())
        if e["kind"] == "aot_cache"
    ]
    assert "hit" in j3_aot
    assert not [n for n in j3_aot if n in ("miss", "store")], (
        "warm job recompiled"
    )


@pytest.mark.slow
def test_scheduler_kill_restart_soak(tmp_path):
    """Multi-round soak: the SIGKILL -> replay -> resume cycle must
    hold up under repetition (fresh root per round)."""
    ref_dir = tmp_path / "ref"
    cli_main([*_SJOB_K, "--save", str(ref_dir)])
    ref = (ref_dir / "result.bin").read_bytes()
    for round_idx in range(3):
        root = tmp_path / f"root{round_idx}"
        submit_to_spool(str(root),
                        JobSpec(job_id="j", argv=list(_SJOB_K)))
        _kill_daemon_mid_job(tmp_path, root, "j", f"soak{round_idx}")
        proc, fh = _launch_daemon(root, tmp_path / "daemon_soak.log")
        try:
            assert proc.wait(timeout=600) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            fh.close()
        assert (root / "jobs" / "j" / "result.bin").read_bytes() == ref
        assert cli_main(["serve", "--root", str(root), "--verify",
                         "--require-complete"]) is None


def test_scheduler_priority_preemption_elastic_roundtrip(tmp_path):
    """A high-priority arrival preempts the running low-priority job
    through the checkpoint-and-exit-75 path; the victim requeues and
    resumes ELASTICALLY on the smaller mesh slice left free (dz=4
    first attempt, dz=2 resume from the same .ckptd) — final state
    bit-exact vs an uninterrupted unsharded run."""
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    low_argv = ["diffusion3d", *GRID, "--iters", "160",
                "--checkpoint-every", "20", "--checkpoint-sharded",
                "--sentinel-every", "20"]
    high_argv = ["diffusion3d", *GRID, "--iters", "60", "--K", "0.8",
                 "--checkpoint-every", "20", "--checkpoint-sharded",
                 "--sentinel-every", "20"]

    ref_dir = tmp_path / "ref"
    cli_main([*low_argv, "--save", str(ref_dir)])
    ref = (ref_dir / "result.bin").read_bytes()

    sched = Scheduler(str(tmp_path / "root"), max_concurrent=2,
                      device_budget=4, poll_seconds=0.05,
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="low", argv=low_argv, priority=0,
                         devices=4, env=env))
    low_dir = sched.job_dir("low")
    deadline = time.time() + 240
    while time.time() < deadline:
        sched.tick()
        if (sched.queue.jobs["low"].state in ("running", "checkpointed")
                and find_latest_checkpoint(low_dir,
                                           report=lambda m: None)):
            break
        time.sleep(0.05)
    else:
        pytest.fail("low never reached a committed checkpoint")

    sched.submit(JobSpec(job_id="high", argv=high_argv, priority=5,
                         devices=2, env=env))
    while time.time() < deadline:
        sched.tick()
        if not sched.queue.open_jobs():
            break
        time.sleep(0.05)
    else:
        pytest.fail(
            f"queue never drained: "
            f"{[(r.job_id, r.state) for r in sched.queue.jobs.values()]}"
        )
    sched.close()

    low, high = sched.queue.jobs["low"], sched.queue.jobs["high"]
    assert low.state == "done" and high.state == "done"
    assert low.attempts == 2  # preempted once, resumed once
    assert low.failures == []  # preemption never burns a retry

    evs = _sched_events(sched.root)
    preempts = [e for e in evs
                if e["kind"] == "sched" and e["name"] == "preempt"]
    assert preempts and preempts[0]["victim"] == "low"
    assert preempts[0]["for_job"] == "high"
    # the journaled chain went through the documented exit-75 path
    chain = [(r.get("from"), r.get("to"))
             for r in _journal_records(sched.root)
             if r.get("type") == "state" and r.get("job") == "low"]
    assert ("preempted", "queued") in chain
    assert os.path.exists(os.path.join(low_dir, "result.bin"))
    # elastic resharded resume: attempt 1 held the full dz=4 slice,
    # attempt 2 restored the same .ckptd onto the free dz=2 slice
    # while high held the other two devices
    starts = {(e["job"], e["attempt"]): e for e in evs
              if e["kind"] == "job" and e["name"] == "start"}
    assert starts[("low", 1)]["mesh"] == "dz=4"
    assert starts[("low", 2)]["mesh"] == "dz=2"
    assert starts[("high", 1)]["mesh"] == "dz=2"

    got = (tmp_path / "root" / "jobs" / "low" / "result.bin").read_bytes()
    assert got == ref, "preempt/resume trajectory diverged"


# --------------------------------------------------------------------- #
# Crash-path telemetry flush (satellite): the JSONL tail survives an
# uncaught structured error — the post-mortem evidence
# --------------------------------------------------------------------- #
def test_crash_event_flushed_on_uncaught_error(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    code = (
        "import os, sys;"
        "os.environ['JAX_PLATFORMS']='cpu';"
        f"sys.path.insert(0, {REPO!r});"
        "from multigpu_advectiondiffusion_tpu import telemetry;"
        "from multigpu_advectiondiffusion_tpu.resilience.errors import "
        "SolverDivergedError;"
        f"telemetry.install({path!r});"
        "telemetry.event('resilience', 'sentinel_armed', cadence=5);"
        "raise SolverDivergedError(7, 0.5, 123.0)"
    )
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode != 0
    events = [
        json.loads(line)
        for line in open(path).read().splitlines()
    ]
    assert events[-1]["kind"] == "crash"
    assert events[-1]["name"] == "SolverDivergedError"
    assert "diverged" in events[-1]["message"]
    # the pre-crash tail survived too
    assert any(
        e["kind"] == "resilience" and e["name"] == "sentinel_armed"
        for e in events
    )
