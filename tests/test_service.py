"""Crash-safe multi-run scheduler (ISSUE 14): tier-1 coverage of the
journal, the queue state machine, admission control, retry policies,
per-job namespacing and the disk-full degradation — everything that
does not need a real SIGKILL (the chaos half lives in test_chaos.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.resilience import faults
from multigpu_advectiondiffusion_tpu.resilience.recovery import (
    find_latest_checkpoint,
)
from multigpu_advectiondiffusion_tpu.service import (
    AdmissionController,
    InProcessRunner,
    Journal,
    JobQueue,
    JobSpec,
    Scheduler,
    WarmLedger,
    classify_failure,
    ingest_spool,
    submit_to_spool,
    verify_records,
)
from multigpu_advectiondiffusion_tpu.service.daemon import (
    FinishedHandle,
    _artifact_rc,
    _flag_value,
)
from multigpu_advectiondiffusion_tpu.telemetry import schema


@pytest.fixture(autouse=True)
def _isolate_aot_cache():
    """In-process workers configure the process-wide AOT cache via
    --aot-cache; restore the knobs so one test's cache directory can
    never leak into another test's dispatches."""
    from multigpu_advectiondiffusion_tpu.tuning import aot_cache

    saved = dict(aot_cache._state)
    yield
    aot_cache._state.clear()
    aot_cache._state.update(saved)


def _events(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def _dead_pid() -> int:
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


_TINY = ["diffusion2d", "--n", "16", "12", "--iters", "6",
         "--checkpoint-every", "3"]


# --------------------------------------------------------------------- #
# Journal: commit records, torn tails, ENOSPC degradation
# --------------------------------------------------------------------- #
def test_journal_roundtrip_and_seq_continuation(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.append("submit", job="a", spec={"x": 1})
        j.append("state", job="a", **{"from": "queued", "to": "admitted"})
    with Journal(path) as j:
        rec = j.append("note", msg="reopened")
    assert rec["seq"] == 3  # sequence continues across incarnations
    records, torn = Journal.replay(path)
    assert torn == 0
    assert [r["seq"] for r in records] == [1, 2, 3]
    assert records[0]["spec"] == {"x": 1}


def test_journal_replay_skips_torn_and_corrupt_lines(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with Journal(path) as j:
        j.append("submit", job="a", spec={})
        j.append("submit", job="b", spec={})
    text = open(path).read().splitlines()
    # text[0] is the schema header (ISSUE 20); corrupt the first
    # PAYLOAD record: a bit-flipped CRC mid-file plus a torn tail
    flipped = text[1].replace('"crc": "', '"crc": "0')[:len(text[1])]
    with open(path, "w") as f:
        f.write(text[0] + "\n" + flipped + "\n" + text[2] + "\n"
                + '{"seq": 3, "ty')
    records, torn = Journal.replay(path)
    assert torn == 2
    assert [r["job"] for r in records] == ["b"]


def test_journal_enospc_degrades_then_heals_in_order(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    with faults.disk_full(targets=("journal",), times=2) as fired:
        rec = j.append("submit", job="a", spec={})
        assert fired["count"] == 2  # first write + its retry
    assert j.degraded and rec["durable"] is False
    # the next append drains the parked record first — order holds
    rec2 = j.append("submit", job="b", spec={})
    assert rec2["durable"] is True and not j.degraded
    j.close()
    records, torn = Journal.replay(path)
    assert torn == 0
    assert [(r["seq"], r["job"]) for r in records] == [(1, "a"), (2, "b")]


# --------------------------------------------------------------------- #
# Queue: transitions, replay, verification, spool
# --------------------------------------------------------------------- #
def test_transition_table_enforced_and_replayed(tmp_path):
    q = JobQueue(Journal(str(tmp_path / "j.jsonl")))
    q.submit(JobSpec(job_id="a", argv=list(_TINY)))
    with pytest.raises(ValueError, match="illegal"):
        q.transition("a", "running")  # queued -> running skips admitted
    q.transition("a", "admitted", granted_devices=2)
    q.transition("a", "running", pid=42, attempt=1)
    q.transition("a", "checkpointed")
    q.transition("a", "preempted")
    q.transition("a", "queued", dt_scale=0.5,
                 failure={"attempt": 1, "policy": "diverged"})
    q2, report = JobQueue.replay(Journal(q.journal.path, fsync=False))
    rec = q2.jobs["a"]
    assert rec.state == "queued"
    assert rec.attempts == 1
    assert rec.dt_scale == 0.5
    assert rec.granted_devices == 0  # freed with the requeue
    assert [f["policy"] for f in rec.failures] == ["diverged"]
    assert report["problems"] == []


def test_verify_records_catches_illegal_and_incomplete(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    q = JobQueue(j)
    q.submit(JobSpec(job_id="a", argv=list(_TINY)))
    q.transition("a", "admitted")
    records, torn = Journal.replay(j.path)
    assert verify_records(records, torn) == []
    # incomplete: --require-complete style check trips
    problems = verify_records(records, torn, require_complete=True)
    assert any("terminal" in p for p in problems)
    # a hand-forged illegal transition record trips the linearizer
    j.append("state", job="a", **{"from": "queued", "to": "done"})
    records, torn = Journal.replay(j.path)
    problems = verify_records(records, torn)
    assert any("illegal" in p or "journal has it" in p
               for p in problems)


def test_spec_rejects_scheduler_owned_flags():
    for flag in ("--save", "--metrics", "--resume", "--mesh",
                 "--aot-cache", "--coordinator", "--dt-scale"):
        with pytest.raises(ValueError, match="scheduler-owned"):
            JobSpec(job_id="x",
                    argv=["diffusion2d", flag, "v"]).validate()


def test_spool_submit_ingest_and_dedupe(tmp_path):
    root = str(tmp_path)
    submit_to_spool(root, JobSpec(job_id="a", argv=list(_TINY)))
    with pytest.raises(ValueError, match="already spooled"):
        submit_to_spool(root, JobSpec(job_id="a", argv=list(_TINY)))
    submit_to_spool(root, JobSpec(job_id="b", argv=list(_TINY),
                                  priority=3))
    q = JobQueue(Journal(os.path.join(root, "journal.jsonl")))
    got = ingest_spool(root, q)
    assert sorted(r.job_id for r in got) == ["a", "b"]
    assert os.listdir(os.path.join(root, "spool")) == []
    # daemon died between journaling and unlinking: the re-spooled
    # duplicate is dropped, not resubmitted
    submit_to_spool(root, JobSpec(job_id="a", argv=list(_TINY)))
    assert ingest_spool(root, q) == []
    assert q.jobs["b"].spec.priority == 3
    assert [r.job_id for r in q.runnable()] == ["b", "a"]  # priority


# --------------------------------------------------------------------- #
# Admission: elastic device grants, memory watermarks, warm ledger
# --------------------------------------------------------------------- #
def test_grant_devices_largest_fitting_divisor():
    a = AdmissionController(device_budget=8)
    assert a.grant_devices(4, 8) == 4
    assert a.grant_devices(4, 3) == 2   # divisor rule, not 3
    assert a.grant_devices(4, 1) == 1
    assert a.grant_devices(4, 0) == 0
    assert a.grant_devices(0, 5) == 1   # unsharded request
    assert a.grant_devices(6, 4) == 3


def test_memory_watermark_defers_until_budget_frees(tmp_path):
    stream = str(tmp_path / "events.jsonl")
    with open(stream, "w") as f:
        f.write(json.dumps({"t": 0.1, "proc": 0, "kind": "mem",
                            "name": "watermark", "bytes_in_use": 100,
                            "peak_bytes": 700, "source": "x"}) + "\n")
        f.write(json.dumps({"t": 0.2, "proc": 0, "kind": "mem",
                            "name": "watermark", "bytes_in_use": 100,
                            "peak_bytes": 800, "source": "x"}) + "\n")
    ledger = WarmLedger()
    spec = JobSpec(job_id="x", argv=list(_TINY))
    from multigpu_advectiondiffusion_tpu.service.admission import (
        latest_watermark,
        warm_key,
    )

    assert latest_watermark(stream) == 800  # the newest peak wins
    ledger.observe(warm_key(spec.argv, None), 1.5, peak_bytes=300)
    ctl = AdmissionController(device_budget=1, mem_budget_bytes=1000,
                              ledger=ledger)
    rec = type("R", (), {"spec": spec})()
    verdict, info = ctl.decide(rec, 1, 1, [stream])
    assert verdict == "defer" and info["reason"] == "memory"
    assert info["mem_in_use"] == 800 and info["mem_estimate"] == 300
    verdict, info = ctl.decide(rec, 1, 1, [])  # the heavy job finished
    assert verdict == "admit"
    assert info["warm"] is True
    assert info["expected_compile_seconds_saved"] == 1.5


# --------------------------------------------------------------------- #
# Retry policies (scripted runner): classification, dt inheritance,
# bounded budgets, the failure ledger
# --------------------------------------------------------------------- #
class ScriptedRunner:
    """Deterministic outcomes per job id; each script step is an rc or
    a callable(job_dir) -> rc that can plant crash evidence first."""

    def __init__(self, script):
        self.script = {k: list(v) for k, v in script.items()}
        self.started = {k: [] for k in script}

    def start(self, argv, env, log_path):
        del env, log_path
        job_dir = _flag_value(argv, "--save")
        job_id = os.path.basename(job_dir)
        self.started[job_id].append(list(argv))
        os.makedirs(job_dir, exist_ok=True)
        step = self.script[job_id].pop(0)
        rc = step(job_dir) if callable(step) else step
        return FinishedHandle(rc)


def _crash(job_dir, etype, message, errno=None):
    with open(os.path.join(job_dir, "crash.json"), "w") as f:
        json.dump({"type": etype, "message": message, "errno": errno}, f)
    return 1


def _drive(sched, max_ticks=50):
    for _ in range(max_ticks):
        sched.tick()
        if not sched.queue.open_jobs():
            return
    raise AssertionError(
        f"queue never drained: "
        f"{[(r.job_id, r.state) for r in sched.queue.jobs.values()]}"
    )


def test_diverged_retries_inherit_dt_backoff(tmp_path):
    runner = ScriptedRunner({
        "a": [
            lambda d: _crash(d, "SolverDivergedError",
                             "diverged at step 7"),
            lambda d: _crash(d, "SolverDivergedError",
                             "diverged at step 9"),
            0,
        ],
    })
    sched = Scheduler(str(tmp_path / "root"), runner=runner,
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="a", argv=list(_TINY), max_retries=2))
    _drive(sched)
    rec = sched.queue.jobs["a"]
    assert rec.state == "done" and rec.attempts == 3
    assert [f["policy"] for f in rec.failures] == ["diverged"] * 2
    # dt-backoff inheritance: attempt 2 starts at 0.5, attempt 3 at 0.25
    argvs = runner.started["a"]
    assert _flag_value(argvs[0], "--dt-scale") is None
    assert float(_flag_value(argvs[1], "--dt-scale")) == 0.5
    assert float(_flag_value(argvs[2], "--dt-scale")) == 0.25
    # ...and the inherited scale is journal-replayable
    q2, _ = JobQueue.replay(Journal(sched.journal.path, fsync=False))
    assert q2.jobs["a"].dt_scale == 0.25
    sched.close()


def test_retry_budget_exhaustion_writes_forensics(tmp_path):
    runner = ScriptedRunner({
        "a": [lambda d: _crash(d, "SolverDivergedError", "boom")] * 3,
        "b": [0],
    })
    sched = Scheduler(str(tmp_path / "root"), runner=runner,
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="a", argv=list(_TINY), max_retries=2))
    sched.submit(JobSpec(job_id="b", argv=list(_TINY)))
    _drive(sched)
    assert sched.queue.jobs["a"].state == "failed"
    assert sched.queue.jobs["b"].state == "done"  # the daemon survived
    forensics = json.loads(
        open(os.path.join(sched.job_dir("a"), "failure.json")).read()
    )
    assert forensics["policy"] == "diverged"
    assert forensics["attempts"] == 3
    # one ledger entry per failed attempt, terminal one included
    assert len(forensics["ledger"]) == 3
    sched.close()


def test_distinct_policies_classified(tmp_path):
    jd = str(tmp_path / "jd")
    os.makedirs(jd)
    assert classify_failure(76, jd)[0] == "rank_failure"
    assert classify_failure(77, jd)[0] == "sdc"
    assert classify_failure(1, jd)[0] == "error"
    _crash(jd, "SDCDetectedError", "duplicate executions differ")
    assert classify_failure(1, jd)[0] == "sdc"
    _crash(jd, "OSError", "No space left on device (injected)",
           errno=28)
    assert classify_failure(1, jd)[0] == "disk_full"
    _crash(jd, "PhysicsViolationError", "tv growth")
    assert classify_failure(1, jd)[0] == "diverged"


def test_preempted_exit_requeues_without_burning_retries(tmp_path):
    runner = ScriptedRunner({"a": [75, 75, 0]})
    sched = Scheduler(str(tmp_path / "root"), runner=runner,
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="a", argv=list(_TINY), max_retries=0))
    _drive(sched)
    rec = sched.queue.jobs["a"]
    # max_retries=0, yet two preemptions did not fail the job — 75 is
    # a requeue, not a failure
    assert rec.state == "done" and rec.attempts == 3
    assert rec.failures == []
    evs = _events(os.path.join(sched.root, "sched_events.jsonl"))
    chain = [(e["from"], e["to"]) for e in evs
             if e["kind"] == "job" and e["name"] == "state"
             and e["job"] == "a"]
    assert chain.count(("running", "preempted")) == 2
    assert chain.count(("preempted", "queued")) == 2
    sched.close()


# --------------------------------------------------------------------- #
# Disk-full degradation (real checkpoint path, in-process worker)
# --------------------------------------------------------------------- #
def test_disk_full_checkpoint_retries_once_then_fails(tmp_path):
    sched = Scheduler(str(tmp_path / "root"),
                      runner=InProcessRunner(), aot_cache=False,
                      fsync=False)
    sched.submit(JobSpec(job_id="nospace", argv=list(_TINY),
                         max_retries=5))
    sched.submit(JobSpec(job_id="fine", argv=list(_TINY)))
    with faults.disk_full(targets=("checkpoint",)):
        for _ in range(20):
            sched.tick()
            if sched.queue.jobs["nospace"].state == "failed":
                break
    _drive(sched)  # the healthy job still completes
    rec = sched.queue.jobs["nospace"]
    # the disk_full policy is bounded at ONE retry regardless of the
    # job's own (generous) max_retries
    assert rec.state == "failed" and rec.attempts == 2
    assert [f["policy"] for f in rec.failures] == ["disk_full"] * 2
    forensics = json.loads(
        open(os.path.join(sched.job_dir("nospace"),
                          "failure.json")).read()
    )
    assert "No space left" in forensics["reason"]
    assert sched.queue.jobs["fine"].state == "done"
    sched.close()


# --------------------------------------------------------------------- #
# Per-job namespacing (satellite): no cross-job checkpoint adoption
# --------------------------------------------------------------------- #
def test_job_namespaces_never_collide(tmp_path):
    sched = Scheduler(str(tmp_path / "root"),
                      runner=InProcessRunner(), aot_cache=False,
                      fsync=False)
    # identical configs, same save ROOT — the classic collision setup
    sched.submit(JobSpec(job_id="a", argv=list(_TINY)))
    sched.submit(JobSpec(job_id="b", argv=list(_TINY)))
    _drive(sched)
    dir_a, dir_b = sched.job_dir("a"), sched.job_dir("b")
    picked_a = find_latest_checkpoint(dir_a)
    picked_b = find_latest_checkpoint(dir_b)
    assert picked_a and picked_a.startswith(dir_a)
    assert picked_b and picked_b.startswith(dir_b)
    assert os.path.dirname(picked_a) != os.path.dirname(picked_b)
    # the resume argv a retry would use scans ONLY the job's own dir
    argv = sched._build_argv(sched.queue.jobs["a"], None)
    assert _flag_value(argv, "--save") == dir_a
    assert dir_b not in " ".join(argv)
    # telemetry sinks are namespaced too: each stream carries exactly
    # its own run, no interleaving
    for jid in ("a", "b"):
        evs = _events(sched.events_path(jid))
        runs = [e for e in evs if e["kind"] == "span"
                and e["name"] == "run_solver"
                and e.get("phase") == "begin"]
        assert len(runs) == 1
    sched.close()


# --------------------------------------------------------------------- #
# Recovery: replay + adopt/classify/requeue (no real SIGKILL here)
# --------------------------------------------------------------------- #
def _plant_journal(root, state_chain, pid=None, job_id="a"):
    j = Journal(os.path.join(root, "journal.jsonl"))
    q = JobQueue(j)
    q.submit(JobSpec(job_id=job_id, argv=list(_TINY)))
    for to in state_chain:
        info = {}
        if to == "running":
            info = {"pid": pid, "attempt": 1}
        elif to == "admitted":
            info = {"granted_devices": 1}
        q.transition(job_id, to, **info)
    j.close()


def test_recover_requeues_dead_inflight_job(tmp_path):
    root = str(tmp_path / "root")
    _plant_journal(root, ["admitted", "running"], pid=_dead_pid())
    runner = ScriptedRunner({"a": [0]})
    sched = Scheduler(root, runner=runner, aot_cache=False, fsync=False)
    rep = sched.recover()
    assert rep["requeued"] == 1 and rep["adopted"] == 0
    assert sched.queue.jobs["a"].state == "queued"
    _drive(sched)
    assert sched.queue.jobs["a"].state == "done"
    # the resume argv carries --resume auto for the recovered attempt
    assert _flag_value(runner.started["a"][0], "--resume") == "auto"
    sched.close()


def test_recover_classifies_finished_orphan_by_artifacts(tmp_path):
    root = str(tmp_path / "root")
    _plant_journal(root, ["admitted", "running"], pid=_dead_pid())
    jd = os.path.join(root, "jobs", "a")
    os.makedirs(jd)
    with open(os.path.join(jd, "summary.json"), "w") as f:
        json.dump({"compile_seconds": 0.2}, f)
    sched = Scheduler(root, runner=ScriptedRunner({"a": []}),
                      aot_cache=False, fsync=False)
    rep = sched.recover()
    assert rep["completed"] == 1
    assert sched.queue.jobs["a"].state == "done"
    sched.close()


def test_recover_requeues_preempted_orphan(tmp_path):
    root = str(tmp_path / "root")
    _plant_journal(root, ["admitted", "running", "checkpointed"],
                   pid=_dead_pid())
    jd = os.path.join(root, "jobs", "a")
    os.makedirs(jd)
    with open(os.path.join(jd, "preempt.json"), "w") as f:
        json.dump({"iteration": 3}, f)
    sched = Scheduler(root, runner=ScriptedRunner({"a": []}),
                      aot_cache=False, fsync=False)
    sched.recover()
    assert sched.queue.jobs["a"].state == "queued"
    records, _ = Journal.replay(sched.journal.path)
    chain = [(r.get("from"), r.get("to")) for r in records
             if r.get("type") == "state"]
    assert ("checkpointed", "preempted") in chain
    assert ("preempted", "queued") in chain
    sched.close()


def test_recover_pid_reuse_guard_blocks_false_adoption(tmp_path):
    # our own (alive) pid, but its cmdline does not mention the job
    # dir: adoption must refuse and requeue instead
    root = str(tmp_path / "root")
    _plant_journal(root, ["admitted", "running"], pid=os.getpid())
    sched = Scheduler(root, runner=ScriptedRunner({"a": [0]}),
                      aot_cache=False, fsync=False)
    rep = sched.recover()
    assert rep["adopted"] == 0 and rep["requeued"] == 1
    sched.close()


def test_artifact_classifier(tmp_path):
    jd = str(tmp_path)
    assert _artifact_rc(jd) == 1
    open(os.path.join(jd, "preempt.json"), "w").write("{}")
    assert _artifact_rc(jd) == 75
    open(os.path.join(jd, "summary.json"), "w").write("{}")
    assert _artifact_rc(jd) == 0


# --------------------------------------------------------------------- #
# Warm admission end to end (in-process): the second identical job
# admits warm and serves every dispatch from the AOT cache
# --------------------------------------------------------------------- #
def test_warm_admission_second_identical_job_hits_aot(tmp_path):
    sched = Scheduler(str(tmp_path / "root"),
                      runner=InProcessRunner(), fsync=False)
    sched.submit(JobSpec(job_id="cold", argv=list(_TINY)))
    sched.submit(JobSpec(job_id="warm", argv=list(_TINY)))
    _drive(sched)
    evs = _events(os.path.join(sched.root, "sched_events.jsonl"))
    admits = {e["job"]: e for e in evs
              if e["kind"] == "sched" and e["name"] == "admit"}
    assert admits["cold"]["warm"] is False
    assert admits["warm"]["warm"] is True
    assert admits["warm"]["expected_compile_seconds_saved"] > 0
    # zero recompiles: the warm job's stream has hits and no miss/store
    warm_evs = _events(sched.events_path("warm"))
    aot = [e["name"] for e in warm_evs if e["kind"] == "aot_cache"]
    assert "hit" in aot
    assert not [n for n in aot if n in ("miss", "store")]
    sched.close()


def test_aot_dispatch_key_separates_physics_scalars():
    """Regression for the cross-job cache collision the scheduler's
    shared per-root AOT cache exposed: two jobs differing only in K
    must never share a serialized executable (dt = c*dx^2/K is a
    compiled-in constant — the K=0.7 job deserializing the K=1.0 blob
    marches the wrong clock)."""
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )
    from multigpu_advectiondiffusion_tpu.tuning import aot_cache

    g = Grid.make(8, 8, lengths=2.0)
    k1 = aot_cache.dispatch_key(
        DiffusionSolver(DiffusionConfig(grid=g, diffusivity=1.0)), "p"
    )
    k2 = aot_cache.dispatch_key(
        DiffusionSolver(DiffusionConfig(grid=g, diffusivity=0.7)), "p"
    )
    k1_again = aot_cache.dispatch_key(
        DiffusionSolver(DiffusionConfig(grid=g, diffusivity=1.0)), "p"
    )
    assert k1 != k2
    assert k1 == k1_again  # deterministic across instances


# --------------------------------------------------------------------- #
# serve --verify CLI + the schema/timeline satellites
# --------------------------------------------------------------------- #
def test_serve_verify_cli_passes_and_trips(tmp_path):
    root = str(tmp_path / "root")
    sched = Scheduler(root, runner=ScriptedRunner({"a": [0]}),
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="a", argv=list(_TINY)))
    _drive(sched)
    sched.close()
    assert cli_main(["serve", "--root", root, "--verify",
                     "--require-complete"]) is None
    # truncating the tail un-terminates the job: --require-complete
    # must trip (the sched_gate.sh selftest fixture)
    lines = open(os.path.join(root, "journal.jsonl")).read().splitlines()
    with open(os.path.join(root, "journal.jsonl"), "w") as f:
        f.write("\n".join(lines[:-1]) + "\n" + lines[-1][:20])
    with pytest.raises(SystemExit):
        cli_main(["serve", "--root", root, "--verify",
                  "--require-complete"])


def test_sched_events_validate_and_render_timeline(tmp_path):
    runner = ScriptedRunner({
        "a": [lambda d: _crash(d, "SolverDivergedError", "x"), 0],
    })
    sched = Scheduler(str(tmp_path / "root"), runner=runner,
                      aot_cache=False, fsync=False)
    sched.submit(JobSpec(job_id="a", argv=list(_TINY), priority=2))
    _drive(sched)
    sched.close()
    stream = os.path.join(sched.root, "sched_events.jsonl")
    for ev in _events(stream):
        assert schema.validate_event(ev) == [], ev
    from multigpu_advectiondiffusion_tpu.telemetry import analyze

    report = analyze.analyze([stream])
    jobs = report.queue["jobs"]
    assert [j["job"] for j in jobs] == ["a"]
    assert jobs[0]["attempts"] == 2
    assert jobs[0]["retries"][0]["policy"] == "diverged"
    states = [p["state"] for p in jobs[0]["states"]]
    assert states[0] == "queued" and states[-1] == "done"
    text = report.format_text()
    assert "job queue timeline" in text
    assert "retry [diverged]" in text
