"""The slab-pipelined whole-run stepper (fused-whole-run-slab).

One Pallas program whose grid is (timestep, z-slab): slabs stream
HBM->VMEM double-buffered, all three RK stages fuse in VMEM per step
(redundant ghost-region recompute, G = 3*stage-radius), state ping-pongs
across steps on a stacked buffer. These tests pin its numerics against
the XLA path (the fused-stage equality tests in test_pallas.py keep
covering the per-stage rung), its dispatch position at the top of the
3-D ladder, and its sharded per-step composition with the ghost
refresh / split-overlap machinery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)

_ULPS = 32 * np.finfo(np.float32).eps


def _rel_close(actual, desired, tol):
    a, d = np.asarray(actual), np.asarray(desired)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) <= tol * scale, (
        float(np.max(np.abs(a - d))) / scale
    )


def test_slab_diffusion_multi_slab_matches_xla():
    """A forced multi-slab pipeline (block_z=4 -> 9 slabs, deep enough
    to engage the cross-step prefetch) must reproduce the generic XLA
    trajectory — the strongest check that the revolving write-drain
    schedule never lets a slab read a neighbor's same-step output."""
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunDiffusionStepper,
        _cross_ok,
    )

    grid = Grid.make(24, 28, 36, lengths=10.0)  # shape (36, 28, 24)
    ref = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="xla")
    )
    want = ref.run(ref.initial_state(), 9)
    st = SlabRunDiffusionStepper(
        grid.shape, jnp.float32, grid.spacing, [1.0] * 3, ref.dt, 2, 0.0,
        block_z=4,
    )
    assert st.n_slabs == 9
    assert _cross_ok(st.bz, st.halo, st.n_slabs), "want the prefetch path"
    st0 = ref.initial_state()
    u, t = jax.jit(lambda u, t: st.run(u, t, 9))(st0.u, st0.t)
    _rel_close(u, want.u, 1e-5)
    assert float(t) == float(want.t)


@pytest.mark.parametrize("order", [5, 7], ids=["weno5", "weno7"])
def test_slab_burgers_multi_slab_matches_xla(order):
    """Multi-slab Burgers (both WENO orders, viscous) vs the XLA path:
    the z-sweep row windows, the in-VMEM edge synthesis at the global
    walls, and the slab chaining must agree with the reference
    discipline across slab boundaries."""
    from multigpu_advectiondiffusion_tpu.ops import flux as flux_lib
    from multigpu_advectiondiffusion_tpu.ops.pallas.fused_slab_run import (
        SlabRunBurgersStepper,
    )

    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    ref = BurgersSolver(
        BurgersConfig(grid=grid, weno_order=order, cfl=0.3, nu=1e-3,
                      adaptive_dt=False, dtype="float32", ic="gaussian",
                      impl="xla")
    )
    want = ref.run(ref.initial_state(), 5)
    st = SlabRunBurgersStepper(
        grid.shape, jnp.float32, grid.spacing, flux_lib.burgers(), "js",
        1e-3, dt=ref.dt, order=order, block_z=4,
    )
    assert st.n_slabs == 4
    st0 = ref.initial_state()
    u, t = jax.jit(lambda u, t: st.run(u, t, 5))(st0.u, st0.t)
    # same rounding classes as the fused-stage-vs-XLA tests: order 7's
    # large beta coefficients widen the band
    _rel_close(u, want.u, 2e-5 if order == 5 else 5e-5)
    assert float(t) == float(want.t)


def test_slab_engagement_ladder():
    """Dispatch: 3-D fixed-dt impl='pallas' engages the slab stepper
    where the model says it wins (small z extents always qualify);
    adaptive dt, t_end mode, bf16 and the 'pallas_stage' pin keep the
    per-stage stepper; 'pallas_slab' pins slab."""
    g3 = Grid.make(24, 16, 16, lengths=2.0)

    def eng(s, mode="iters"):
        return s.engaged_path(mode)["stepper"]

    d = DiffusionSolver(DiffusionConfig(grid=g3, dtype="float32",
                                        impl="pallas"))
    assert eng(d) == "fused-whole-run-slab"
    assert eng(d, "t_end") == "fused-stage"  # slab has no run_to
    assert eng(DiffusionSolver(DiffusionConfig(
        grid=g3, dtype="float32", impl="pallas_stage"))) == "fused-stage"
    assert eng(DiffusionSolver(DiffusionConfig(
        grid=g3, dtype="float32", impl="pallas_slab"))) == (
        "fused-whole-run-slab"
    )
    assert eng(DiffusionSolver(DiffusionConfig(
        grid=g3, dtype="bfloat16", impl="pallas"))) == "fused-stage"

    b = BurgersSolver(BurgersConfig(grid=g3, nu=1e-5, adaptive_dt=False,
                                    dtype="float32", impl="pallas"))
    assert eng(b) == "fused-whole-run-slab"
    assert eng(b, "t_end") == "fused-stage"
    assert eng(BurgersSolver(BurgersConfig(
        grid=g3, nu=1e-5, adaptive_dt=True, dtype="float32",
        impl="pallas"))) == "fused-stage"

    # profitability: a deep-z grid whose slabs come out thin keeps the
    # measured per-stage path under plain 'pallas' (the redundant
    # recompute tax), but 'pallas_slab' still pins slab
    from multigpu_advectiondiffusion_tpu.ops.pallas import fused_slab_run

    assert not fused_slab_run.SlabRunBurgersStepper.profitable(
        (512, 512, 512), jnp.float32
    )
    assert not fused_slab_run.SlabRunDiffusionStepper.profitable(
        (160, 204, 508), jnp.float32
    )


def test_slab_pallas_stage_pin_matches_xla():
    """impl='pallas_stage' pins the per-stage stepper for unsharded
    fixed-dt configs (the rung 'pallas' used to select) and matches XLA
    — keeps the per-stage fixed-dt path covered now that 'pallas'
    prefers the slab stepper."""
    grid = Grid.make(24, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                        dtype="float32", impl="pallas_stage")
    s = BurgersSolver(cfg)
    fused = s._fused_stepper()
    assert fused is not None and fused.engaged_label == "fused-stage"
    out = s.run(s.initial_state(), 5)
    ref = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                      dtype="float32", impl="xla")
    )
    want = ref.run(ref.initial_state(), 5)
    _rel_close(out.u, want.u, 2e-5)


def test_slab_diffusion_f64_storage_matches_xla_f64():
    """The f64-storage/f32-compute rung: state stays f64, kernels run
    f32 — the trajectory must match the XLA f64 path to f32 accuracy,
    and the returned state must still be f64 (the storage half of the
    convention)."""
    grid = Grid.make(24, 16, 16, lengths=2.0)
    sp = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float64",
                                         impl="pallas"))
    assert sp.engaged_path()["stepper"] in (
        "fused-whole-run-slab", "fused-stage"
    )
    out = sp.run(sp.initial_state(), 5)
    assert out.u.dtype == jnp.float64
    sx = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float64",
                                         impl="xla"))
    want = sx.run(sx.initial_state(), 5)
    _rel_close(out.u, want.u, 1e-5)
    # f64 Burgers stays off the fused ladder (kernels are f32-only and
    # Burgers has no storage rung)
    bf = BurgersSolver(BurgersConfig(grid=grid, dtype="float64",
                                     impl="pallas"))
    assert bf._fused_stepper() is None


def test_slab_sharded_zslab_split_matches_unsharded(devices):
    """The sharded slab composition (pinned via impl='pallas_slab',
    z-slab mesh): per-step slab-pipelined calls with ONE G-deep z-ghost
    exchange per step. overlap='split' runs the three-call schedule
    (interior slabs concurrent with the in-flight ppermute; the two
    edge calls consume the exchanged G-slabs) and must match the
    unsharded whole-run stepper — diffusion bit-for-bit (identical
    per-cell op sequence), Burgers to the interpret-mode ulp bound."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    # diffusion: local lz=36 -> split picks bz=12, n_slabs=3
    grid = Grid.make(16, 16, 72, lengths=2.0)
    ref_s = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab")
    )
    assert ref_s._fused_stepper().engaged_label == "fused-whole-run-slab"
    ref = ref_s.run(ref_s.initial_state(), 4)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab",
                          overlap="split")
    s = DiffusionSolver(cfg, mesh=make_mesh({"dz": 2}),
                        decomp=Decomposition.slab("dz"))
    f = s._fused_stepper()
    assert f is not None and f.sharded and f.overlap_split, (
        getattr(s, "_fused_fallback", None), f and f.n_slabs
    )
    assert f.engaged_label == "fused-whole-run-slab"
    assert s.engaged_path()["overlap"] == "split"
    out = s.run(s.initial_state(), 4)
    assert float(jnp.max(jnp.abs(out.u - ref.u))) == 0.0
    assert float(out.t) == float(ref.t)

    # burgers: local lz=30 -> split picks bz=10 (>= G=9), n_slabs=3
    grid = Grid.make(16, 16, 60, lengths=2.0)
    ref_b = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                      dtype="float32", impl="pallas_slab")
    )
    refu = ref_b.run(ref_b.initial_state(), 4)
    cfgb = BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                         dtype="float32", impl="pallas_slab",
                         overlap="split")
    sb = BurgersSolver(cfgb, mesh=make_mesh({"dz": 2}),
                       decomp=Decomposition.slab("dz"))
    fb = sb._fused_stepper()
    assert fb is not None and fb.overlap_split, (
        getattr(sb, "_fused_fallback", None), fb and (fb.bz, fb.n_slabs)
    )
    outb = sb.run(sb.initial_state(), 4)
    a, d = np.asarray(outb.u), np.asarray(refu.u)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) <= _ULPS * scale
    assert float(outb.t) == float(refu.t)


def test_slab_sharded_serialized_refresh_matches_unsharded(devices):
    """The serialized per-step G-deep refresh (no split): one exchange
    + one slab-pipelined call per step, bit-identical to the unsharded
    slab run for diffusion."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 72, lengths=2.0)
    ref_s = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab")
    )
    ref = ref_s.run(ref_s.initial_state(), 4)
    s = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    f = s._fused_stepper()
    assert f is not None and f.sharded and not f.overlap_split
    assert s.engaged_path()["overlap"] == "serialized-refresh"
    out = s.run(s.initial_state(), 4)
    assert float(jnp.max(jnp.abs(out.u - ref.u))) == 0.0


def test_slab_dma_exchange_matches_collective(devices):
    """The in-kernel remote-DMA exchange (exchange='dma', ISSUE 13):
    the sharded whole-run program pushes its ghost rows to the ±z
    neighbors from inside the Pallas kernel instead of breaking out to
    an XLA ppermute between per-step calls. Same rows move, same
    per-cell op sequence computes — diffusion must match the collective
    transport bit-for-bit, at both the per-step (k=1) and the deep
    (k=2) exchange cadence, in interpret mode on a dz=2 mesh."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 72, lengths=2.0)
    for k in (1, 2):
        ref_s = DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", steps_per_exchange=k),
            mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
        )
        ref = ref_s.run(ref_s.initial_state(), 5)
        s = DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", steps_per_exchange=k,
                            exchange="dma"),
            mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
        )
        eng = s.engaged_path()
        assert eng["stepper"] == "fused-whole-run-slab"
        assert eng["exchange"] == "dma"
        assert eng["overlap"] == "in-kernel"
        fused = s._fused_stepper()
        spec = fused.stencil_spec()
        assert spec["remote_dma"] is not None
        assert spec["remote_dma"]["window_rows"] == fused.exchange_depth
        out = s.run(s.initial_state(), 5)
        assert float(jnp.max(jnp.abs(out.u - ref.u))) == 0.0, k
        assert float(out.t) == float(ref.t)


def test_slab_dma_burgers_matches_collective(devices):
    """WENO5 Burgers through the dma transport vs the collective
    transport: identical consumed values (the wall replicas are
    re-synthesized in VMEM either way), ulp-level equality like every
    sharded WENO pin."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 72, lengths=2.0)
    ref_b = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                      dtype="float32", impl="pallas_slab"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    ref = ref_b.run(ref_b.initial_state(), 4)
    sb = BurgersSolver(
        BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                      dtype="float32", impl="pallas_slab",
                      exchange="dma"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    assert sb.engaged_path()["exchange"] == "dma"
    out = sb.run(sb.initial_state(), 4)
    a, d = np.asarray(out.u), np.asarray(ref.u)
    scale = max(float(np.max(np.abs(d))), 1e-30)
    assert float(np.max(np.abs(a - d))) <= _ULPS * scale
    assert float(out.t) == float(ref.t)


def test_slab_dma_declines_loudly(devices):
    """exchange='dma' is pin-semantics: every config that cannot host
    the in-kernel exchange fails at construction/dispatch instead of
    silently running the collective cadence — unsharded, pencil
    meshes, split-overlap, non-TPU/non-interpret backends, and the
    batched ensemble engine."""
    from multigpu_advectiondiffusion_tpu.models.ensemble import (
        EnsembleSolver,
    )
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 72, lengths=2.0)

    # unsharded: no neighbor to push to
    with pytest.raises(ValueError, match="needs a device mesh"):
        DiffusionSolver(DiffusionConfig(grid=grid, dtype="float32",
                                        impl="pallas_slab",
                                        exchange="dma"))
    # pencil mesh: the remote-DMA ring is z-slab only
    with pytest.raises(ValueError, match="z-slab"):
        DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", exchange="dma"),
            mesh=make_mesh({"dz": 2, "dy": 2}),
            decomp=Decomposition.of({0: "dz", 1: "dy"}),
        )
    # split-overlap: nothing left at the XLA level to overlap
    with pytest.raises(ValueError, match="split-overlap"):
        DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", exchange="dma",
                            overlap="split"),
            mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
        )
    # generic impl cannot host it
    with pytest.raises(ValueError, match="sharded slab rung"):
        DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32", impl="xla",
                            exchange="dma"),
            mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
        )
    # ensemble fold: the member axis does not ride the dma ring (the
    # inner solver is unsharded-spatial, so the mesh gate fires first;
    # _ensemble_gate backstops the batched dispatch itself)
    with pytest.raises(ValueError, match="dma"):
        EnsembleSolver(
            DiffusionSolver,
            DiffusionConfig(grid=grid, dtype="float32",
                            impl="pallas_slab", exchange="dma"),
            4,
        )


def test_slab_dma_backend_gate(devices, monkeypatch):
    """A backend with neither the Mosaic TPU target nor the CPU
    interpret simulator (i.e. a real CPU/GPU lowering) declines the
    dma rung LOUDLY at dispatch — never a silent collective run."""
    from multigpu_advectiondiffusion_tpu.ops.pallas import laplacian
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    s = DiffusionSolver(
        DiffusionConfig(grid=Grid.make(16, 16, 72, lengths=2.0),
                        dtype="float32", impl="pallas_slab",
                        exchange="dma"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    monkeypatch.setattr(laplacian, "interpret_mode", lambda: False)
    with pytest.raises(ValueError, match="remote DMA needs the TPU"):
        s._fused_stepper()


def test_slab_dma_mosaic_rejection_degrades_to_split(devices, monkeypatch):
    """The dma rung's own ladder: a Mosaic rejection of the in-kernel
    program degrades to the split-overlap COLLECTIVE exchange on the
    same rung/cadence (recorded in engaged_path()['degraded']), and the
    run completes with the collective trajectory."""
    from multigpu_advectiondiffusion_tpu.ops.pallas import fused_slab_run
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        SimulatedMosaicError,
    )

    grid = Grid.make(16, 16, 72, lengths=2.0)
    ref_s = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    ref = ref_s.run(ref_s.initial_state(), 4)

    def boom(self, *a, **kw):
        raise SimulatedMosaicError("Mosaic rejected the dma program")

    monkeypatch.setattr(
        fused_slab_run._SlabRunStepper, "_run_dma", boom
    )
    s = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas_slab",
                        exchange="dma"),
        mesh=make_mesh({"dz": 2}), decomp=Decomposition.slab("dz"),
    )
    out = s.run(s.initial_state(), 4)
    engaged = s.engaged_path()
    assert engaged["stepper"] == "fused-whole-run-slab"
    assert engaged["exchange"] == "collective"
    chain = [(e["from"], e["to"]) for e in engaged["degraded"]]
    assert chain == [
        ("fused-whole-run-slab[dma]", "fused-whole-run-slab[split]")
    ]
    assert float(jnp.max(jnp.abs(out.u - ref.u))) == 0.0


def test_slab_sharded_declines_off_design(devices):
    """Sharded slab stays pinned-only and z-slab-only: plain 'pallas'
    under a mesh keeps the measured per-stage path, pencil meshes
    decline the pin, and 'pallas' on a y-sharded mesh is untouched by
    the slab machinery."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(16, 16, 48, lengths=2.0)
    cfg = BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                        dtype="float32", impl="pallas")
    s = BurgersSolver(cfg, mesh=make_mesh({"dz": 2}),
                      decomp=Decomposition.slab("dz"))
    assert s.engaged_path()["stepper"] == "fused-stage"

    pin = BurgersConfig(grid=grid, nu=1e-5, adaptive_dt=False,
                        dtype="float32", impl="pallas_slab")
    sp = BurgersSolver(pin, mesh=make_mesh({"dz": 2, "dy": 2}),
                       decomp=Decomposition.of({0: "dz", 1: "dy"}))
    # pencil mesh: slab pin declines to per-stage (still fused)
    assert sp.engaged_path()["stepper"] == "fused-stage"
