"""Distributed-runtime tests on the simulated 8-device CPU mesh.

The key property the reference could never test (SURVEY §4: no fake
backend, ``MPIDeviceCheck`` exits without >= 2 physical GPUs): a sharded
run must be **bit-identical** to the unsharded run — the halo exchange
(``lax.ppermute``), global-edge BC fix-up, and ``pmax`` CFL reduction may
not change a single ulp.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition, make_mesh


def _max_abs_diff(a, b):
    return float(jnp.max(jnp.abs(a - b)))


# WENO sharded-vs-unsharded bound: the single-division weight form
# (ops/weno.py _weno5_alphas_unnormalized) is a chain of multiplies whose
# FMA contraction XLA chooses per program shape, so shard-local and
# global compilations may round differently by a few ulps per step, and
# the nonlinear weights compound that over the 5-step runs below
# (measured: ~11 ulps at step 5). Diffusion stays exactly bit-identical
# (its linear stencil leaves XLA no such freedom). float64 eps because
# every WENO config below runs dtype="float64"; the float32 analog lives
# in test_multihost.py.
_WENO_ULPS = 32 * np.finfo(np.float64).eps


@pytest.mark.parametrize(
    "mesh_axes,decomp_map",
    [
        ({"dz": 4}, {0: "dz"}),  # reference-style slab over z
        ({"dz": 2, "dy": 2}, {0: "dz", 1: "dy"}),  # 2-D pencils
        ({"dz": 2, "dy": 2, "dx": 2}, {0: "dz", 1: "dy", 2: "dx"}),  # 3-D blocks
    ],
)
def test_diffusion3d_sharded_bit_identical(devices, mesh_axes, decomp_map):
    grid = Grid.make(24, 24, 24, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float64")
    mesh = make_mesh(mesh_axes)
    ref = DiffusionSolver(cfg).run(DiffusionSolver(cfg).initial_state(), 10)
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.of(decomp_map))
    out = solver.run(solver.initial_state(), 10)
    assert _max_abs_diff(ref.u, out.u) == 0.0


@pytest.mark.parametrize("variant", ["js", "z"])
def test_burgers3d_sharded_bit_identical(devices, variant):
    """Adaptive dt: the global max|u| reduction must also agree (pmax)."""
    grid = Grid.make(16, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_variant=variant, nu=1e-5, dtype="float64")
    mesh = make_mesh({"dz": 2, "dy": 2})
    ref = BurgersSolver(cfg).run(BurgersSolver(cfg).initial_state(), 5)
    solver = BurgersSolver(
        cfg, mesh=mesh, decomp=Decomposition.of({0: "dz", 1: "dy"})
    )
    out = solver.run(solver.initial_state(), 5)
    assert _max_abs_diff(ref.u, out.u) <= _WENO_ULPS
    # adaptive dt inherits the state's few-ulp freedom through the CFL
    # max, so the accumulated t may differ in the last ulp or two —
    # demand ulp-level, not bit-level, agreement
    assert abs(float(ref.t) - float(out.t)) <= (
        8 * np.finfo(np.float64).eps * max(1.0, abs(float(ref.t)))
    )


def test_burgers3d_weno7_sharded(devices):
    """3-D WENO7 (halo 4) under a pencil mesh on the generic path: the
    4-deep ppermute exchange must reproduce the unsharded trajectory
    (adaptive dt, so the pmax reduction is exercised too)."""
    grid = Grid.make(16, 16, 16, lengths=2.0)
    cfg = BurgersConfig(grid=grid, weno_order=7, nu=1e-5, dtype="float64")
    mesh = make_mesh({"dz": 2, "dy": 2})
    ref = BurgersSolver(cfg).run(BurgersSolver(cfg).initial_state(), 5)
    solver = BurgersSolver(
        cfg, mesh=mesh, decomp=Decomposition.of({0: "dz", 1: "dy"})
    )
    out = solver.run(solver.initial_state(), 5)
    assert _max_abs_diff(ref.u, out.u) <= _WENO_ULPS
    # adaptive dt inherits the state's few-ulp freedom through the CFL
    # max, so the accumulated t may differ in the last ulp or two —
    # demand ulp-level, not bit-level, agreement
    assert abs(float(ref.t) - float(out.t)) <= (
        8 * np.finfo(np.float64).eps * max(1.0, abs(float(ref.t)))
    )


def test_burgers2d_sharded_innermost_axis(devices):
    """Sharding the x (innermost/lane) axis exercises the awkward sweep."""
    grid = Grid.make(32, 32, lengths=2.0)
    cfg = BurgersConfig(grid=grid, dtype="float64")
    mesh = make_mesh({"dx": 4})
    ref = BurgersSolver(cfg).run(BurgersSolver(cfg).initial_state(), 5)
    solver = BurgersSolver(cfg, mesh=mesh, decomp=Decomposition.of({1: "dx"}))
    out = solver.run(solver.initial_state(), 5)
    assert _max_abs_diff(ref.u, out.u) <= _WENO_ULPS


def test_periodic_sharded(devices):
    grid = Grid.make(32, 32, lengths=2.0)
    cfg = BurgersConfig(grid=grid, bc="periodic", dtype="float64")
    mesh = make_mesh({"dy": 4})
    ref = BurgersSolver(cfg).run(BurgersSolver(cfg).initial_state(), 5)
    solver = BurgersSolver(cfg, mesh=mesh, decomp=Decomposition.of({0: "dy"}))
    out = solver.run(solver.initial_state(), 5)
    assert _max_abs_diff(ref.u, out.u) <= _WENO_ULPS


def test_sharded_output_sharding_preserved(devices):
    grid = Grid.make(24, 24, 24, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32")
    mesh = make_mesh({"dz": 8})
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    out = solver.run(solver.initial_state(), 3)
    assert out.u.sharding.is_equivalent_to(solver.sharding(), grid.ndim)


def test_axisymmetric_sharded_r_axis(devices):
    """Sharding r exercises the 1/r local-window slice (diffusion.py)."""
    grid = Grid.make(32, 32, bounds=[(-4.0, 4.0), (-4.0, 4.0)])
    cfg = DiffusionConfig(
        grid=grid, geometry="axisymmetric", diffusivity=0.5, t0=1.0,
        bc=("edge", "dirichlet"), dtype="float64",
    )
    mesh = make_mesh({"dr": 4})
    ref = DiffusionSolver(cfg).run(DiffusionSolver(cfg).initial_state(), 5)
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.of({1: "dr"}))
    out = solver.run(solver.initial_state(), 5)
    assert _max_abs_diff(ref.u, out.u) == 0.0


def test_hybrid_mesh_single_slice_runs_sharded_step():
    """hybrid_mesh with a trivial DCN extent must build a usable mesh on
    platforms without slice topology (the virtual-CPU rig) and drive the
    sharded solver exactly like make_mesh."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import hybrid_mesh

    mesh = hybrid_mesh({"dz": 8}, {"dz_dcn": 1})
    assert mesh.axis_names == ("dz_dcn", "dz")
    assert dict(mesh.shape) == {"dz_dcn": 1, "dz": 8}

    grid = Grid.make(16, 16, 16, lengths=4.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32")
    ref = DiffusionSolver(cfg).run(DiffusionSolver(cfg).initial_state(), 3)
    sharded = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    out = sharded.run(sharded.initial_state(), 3)
    # f32 + 2-cell shards: compiled-program FMA fusion may differ at ulp
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               rtol=1e-6, atol=1e-7)


def test_hybrid_mesh_rejects_wrong_device_count():
    """A size mismatch must stay a loud error, not a silent
    subset-of-devices mesh."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import hybrid_mesh

    with pytest.raises(ValueError):
        hybrid_mesh({"dz": 4}, {"dz_dcn": 1})  # 4 != the rig's 8 devices


def test_hybrid_mesh_multi_slice_unavailable_raises_cleanly():
    """With a real DCN extent on a platform without slice/process
    topology the failure must be a ValueError, not an attribute crash."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import hybrid_mesh

    with pytest.raises(ValueError):
        hybrid_mesh({"dz": 4}, {"dz_dcn": 2})


@pytest.mark.parametrize(
    "mesh_axes,decomp_map",
    [
        ({"dz": 4}, {0: "dz"}),
        ({"dz": 2, "dy": 2}, {0: "dz", 1: "dy"}),
    ],
)
def test_diffusion3d_split_overlap_bit_identical(devices, mesh_axes,
                                                 decomp_map):
    """overlap='split' (interior concurrent with in-flight ghost
    collectives, bands patched after) must be bitwise equal to the
    padded schedule AND to the unsharded run at ulp level — same
    stencil over the same values; only FMA-fusion choices may differ
    between the two compiled programs."""
    grid = Grid.make(24, 24, 24, lengths=10.0)
    mesh = make_mesh(mesh_axes)
    ref = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float64")
    )
    ref_out = ref.run(ref.initial_state(), 10)
    split = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float64", overlap="split"),
        mesh=mesh, decomp=Decomposition.of(decomp_map),
    )
    out = split.run(split.initial_state(), 10)
    scale = float(jnp.max(jnp.abs(ref_out.u)))
    assert _max_abs_diff(ref_out.u, out.u) <= 4 * np.finfo(np.float64).eps * scale


def test_burgers3d_split_overlap_matches_padded(devices):
    """Split schedule for the WENO sweeps + viscous Laplacian under an
    adaptive-dt sharded run (pmax reduction in the loop)."""
    grid = Grid.make(16, 16, 16, lengths=4.0)
    mesh = make_mesh({"dz": 4})
    outs = {}
    for overlap in ("padded", "split"):
        cfg = BurgersConfig(grid=grid, nu=1e-4, dtype="float64",
                            ic="gaussian", overlap=overlap)
        s = BurgersSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
        outs[overlap] = s.run(s.initial_state(), 6)
    scale = float(jnp.max(jnp.abs(outs["padded"].u)))
    assert _max_abs_diff(outs["padded"].u, outs["split"].u) <= (
        16 * np.finfo(np.float64).eps * scale
    )
    np.testing.assert_allclose(float(outs["padded"].t),
                               float(outs["split"].t), rtol=1e-14)


def test_split_overlap_tiny_shard_falls_back(devices):
    """Shards narrower than 2 x halo take the unsplit path inside
    split_axis_apply and still match the padded schedule."""
    # 8 cells over 4 shards -> 2 cells/shard < 2*r for the O4 Laplacian
    # halo of 2? (2*2=4 > 2) -> fallback branch exercised
    grid = Grid.make(12, 12, 8, lengths=4.0)
    mesh = make_mesh({"dz": 4})
    outs = {}
    for overlap in ("padded", "split"):
        cfg = DiffusionConfig(grid=grid, dtype="float64", overlap=overlap)
        s = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
        outs[overlap] = s.run(s.initial_state(), 4)
    scale = float(jnp.max(jnp.abs(outs["padded"].u)))
    assert _max_abs_diff(outs["padded"].u, outs["split"].u) <= (
        4 * np.finfo(np.float64).eps * scale
    )


def test_sharded_pallas_impl_matches_xla(devices):
    """Sharded runs with impl='pallas' (per-axis VMEM kernels fed by
    ppermute halos inside shard_map) must match the sharded XLA path."""
    grid = Grid.make(24, 16, 16, lengths=4.0)
    mesh = make_mesh({"dz": 4})
    outs = {}
    for impl in ("xla", "pallas"):
        cfg = DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        s = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
        outs[impl] = np.asarray(s.run(s.initial_state(), 4).u)
    np.testing.assert_allclose(outs["pallas"], outs["xla"],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize(
    "mesh_axes,decomp_map",
    [
        ({"dz": 4}, {0: "dz"}),  # reference-style z slabs
        ({"dz": 2, "dy": 2}, {0: "dz", 1: "dy"}),  # pencils
        ({"dz": 2, "dy": 2, "dx": 2}, {0: "dz", 1: "dy", 2: "dx"}),  # blocks
    ],
)
def test_fused_diffusion_sharded_bit_identical_to_unsharded_fused(
    devices, mesh_axes, decomp_map
):
    """The fused per-stage Pallas stepper running shard-local inside
    shard_map (ppermute ghost refresh between stages, global wall masks
    via the offsets operand) must reproduce the single-device fused run
    bit-for-bit — same per-cell arithmetic over the same values; the
    ghost refresh may not change an ulp."""
    grid = Grid.make(24, 16, 16, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    ref_solver = DiffusionSolver(cfg)
    assert ref_solver._fused_stepper() is not None
    ref = ref_solver.run(ref_solver.initial_state(), 8)

    mesh = make_mesh(mesh_axes)
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.of(decomp_map))
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded
    out = solver.run(solver.initial_state(), 8)
    assert _max_abs_diff(ref.u, out.u) == 0.0


def test_fused_diffusion_sharded_minimal_shards(devices):
    """2-cell shards: every shard is the minimum that can serve the O4
    halo, and the edge shards lie entirely inside the frozen boundary
    band — the offsets operand must keep those global-index decisions
    right."""
    grid = Grid.make(16, 16, 16, lengths=4.0)
    cfg = DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    ref_solver = DiffusionSolver(cfg)
    ref = ref_solver.run(ref_solver.initial_state(), 4)
    mesh = make_mesh({"dz": 8})
    solver = DiffusionSolver(cfg, mesh=mesh, decomp=Decomposition.slab("dz"))
    fused = solver._fused_stepper()
    assert fused is not None and fused.sharded
    out = solver.run(solver.initial_state(), 4)
    assert _max_abs_diff(ref.u, out.u) == 0.0


def test_cli_style_pallas_step_on_burgers_falls_back():
    """A global --impl pallas_step applied to Burgers must run the
    per-axis pallas kernels, not crash in the WENO dispatcher."""
    grid = Grid.make(16, 12, 12, lengths=4.0)
    cfg = BurgersConfig(grid=grid, ic="gaussian", impl="pallas_step",
                        adaptive_dt=True)
    s = BurgersSolver(cfg)
    out = s.run(s.initial_state(), 2)
    assert np.isfinite(np.asarray(out.u)).all()
