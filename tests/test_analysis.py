"""Static-analysis subsystem (ISSUE 10): the AST lint rules, the
stencil/halo consistency verifier, and the checkify sanitizer.

Tier-1 teeth:

* the whole installed package must lint clean — a future non-atomic
  write, closure-captured override, host sync in traced code or
  unregistered emission fails HERE, not in production six months on;
* every rule trips on its seeded violation fixture and stays silent on
  the clean twin (a green gate means "checked and clean", never
  "checker broke");
* the halo verifier proves every dispatch-admitted (rung, order, k)
  combination and fails an injected off-by-one ghost depth loudly,
  naming kernel/axis/depth;
* ``--checkify`` catches an injected NaN (named, at the offending
  primitive) through the supervisor's rollback path BEFORE the
  divergence sentinel's norm probe would notice.
"""

from __future__ import annotations

import os
import tempfile

import jax.numpy as jnp
import pytest

from multigpu_advectiondiffusion_tpu.analysis import (
    all_rules,
    collective_verify,
    halo_verify,
    run_rules,
    sanitizer,
)
from multigpu_advectiondiffusion_tpu.analysis.fixtures import RULE_FIXTURES
from multigpu_advectiondiffusion_tpu.utils.io import atomic_write_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "multigpu_advectiondiffusion_tpu")


# --------------------------------------------------------------------- #
# Lint rules
# --------------------------------------------------------------------- #
def test_package_tree_lints_clean():
    violations = run_rules(PKG)
    assert not violations, (
        "tpucfd-check flags the shipped tree:\n"
        + "\n".join(str(v) for v in violations)
    )


def test_every_rule_has_a_fixture():
    assert set(all_rules()) == set(RULE_FIXTURES)


def _lint_fixture(rule_name: str, src: str):
    rule = all_rules()[rule_name]()
    with tempfile.TemporaryDirectory() as d:
        atomic_write_text(os.path.join(d, "fixture.py"), src)
        return [v for v in run_rules(d, rules=[rule])
                if v.rule == rule_name]


@pytest.mark.parametrize("rule_name", sorted(RULE_FIXTURES))
def test_rule_trips_on_seeded_violation(rule_name):
    hits = _lint_fixture(rule_name, RULE_FIXTURES[rule_name]["bad"])
    assert hits, f"rule {rule_name} missed its seeded violation"
    assert all(v.rule == rule_name and v.line > 0 for v in hits)


@pytest.mark.parametrize("rule_name", sorted(RULE_FIXTURES))
def test_rule_passes_clean_twin(rule_name):
    hits = _lint_fixture(rule_name, RULE_FIXTURES[rule_name]["good"])
    assert not hits, [str(v) for v in hits]


def test_suppression_pragma_is_honored():
    src = RULE_FIXTURES["raw-artifact-write"]["bad"].replace(
        "    with open(path, 'w') as f:",
        "    # tpucfd-check: allow[raw-artifact-write] — test pragma\n"
        "    with open(path, 'w') as f:",
    )
    assert not _lint_fixture("raw-artifact-write", src)


def test_scan_emitted_rides_the_engine():
    """The migrated schema scanner (satellite 2): same contract as the
    regex it replaced — real sites found, dynamic names as None."""
    from multigpu_advectiondiffusion_tpu.telemetry import schema

    pairs, counters = schema.scan_emitted(PKG)
    assert ("dispatch", "build") in pairs
    assert ("resilience", "rollback") in pairs
    assert ("sanitizer", "trip") in pairs
    # RunSummary emits under a run-named (dynamic) event name
    assert ("summary", None) in pairs
    assert "halo.exchanges_traced" in counters


# --------------------------------------------------------------------- #
# Stencil/halo verifier
# --------------------------------------------------------------------- #
def test_halo_verifier_proves_all_admitted_combos():
    report = halo_verify.verify_all()
    assert report.ok, "\n".join(str(v) for v in report.violations)
    names = {c.name for c in report.combos if c.admitted}
    # the matrix genuinely spans (rung, order, k) AND — since the
    # mesh-scale ensemble round — the member axis: B-folded slab
    # instances and the member-sharded mesh layouts
    for expect in (
        "diffusion3d-stage", "diffusion3d-stage[sharded]",
        "diffusion3d-step", "diffusion2d-whole-run",
        "slab-diffusion[k=1]", "slab-diffusion[k=2]",
        "slab-diffusion[k=3]", "slab-diffusion[k=2,split]",
        "burgers3d-stage[o5]", "burgers3d-stage[o7,sharded]",
        "slab-burgers[o5,k=2]", "slab-burgers[o7,k=2,split]",
        "burgers2d-stage[o7,sharded]",
        "slab-diffusion[B=2]", "slab-diffusion[B=4]",
        "slab-burgers[o5,B=4]", "slab-burgers[o7,B=4]",
        "ensemble-mesh[members=8]", "ensemble-mesh[members=4,dz=2]",
        # in-kernel remote-DMA transport (ISSUE 13): the shipped
        # declaration proven per admitted cadence and order
        "slab-diffusion[k=1,dma]", "slab-diffusion[k=3,dma]",
        "slab-burgers[o5,k=1,dma]", "slab-burgers[o5,k=3]",
        "slab-burgers[o7,k=2,dma]", "slab-burgers[o7,k=3,dma]",
    ):
        assert expect in names, f"combo {expect} missing from the matrix"
    assert report.checked >= 49
    # the spatially sharded member fold must DECLINE (constructor
    # gate), mirroring the dispatch's loud rejection — never verify
    declined = {c.name: c.reason for c in report.combos
                if not c.admitted}
    assert "slab-diffusion[B=4,sharded]" in declined
    assert "member" in declined["slab-diffusion[B=4,sharded]"]


def test_member_axis_violations_fail_loudly():
    """Injected member-axis faults are named: a nonzero member halo on
    a B-folded instance, and a members axis leaking into the spatial
    decomposition."""
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[B=4]"
    )
    stepper = combo.build()
    stepper.member_halo = 1  # the cross-member read a refactor could slip
    violations = halo_verify.verify_stepper(
        stepper, kernel="slab-diffusion[B=4]"
    )
    assert any("halo-free" in v.what for v in violations)
    res = halo_verify.verify_member_mesh(
        "bad-mesh", {"members": 4, "dz": 2}, {0: "members"}
    )
    assert res.violations
    assert any("may not shard a grid axis" in v.what
               for v in res.violations)


def test_constants_cross_check_from_first_principles():
    assert not halo_verify.verify_constants()


@pytest.mark.parametrize("combo_name", [
    "slab-diffusion[k=2]", "slab-burgers[o5,k=2]",
])
def test_injected_off_by_one_ghost_depth_fails_loudly(combo_name):
    combo = next(
        c for c in halo_verify.default_combos() if c.name == combo_name
    )
    stepper = combo.build()
    stepper.exchange_depth += 1  # the off-by-one a refactor could slip
    violations = halo_verify.verify_stepper(stepper, kernel=combo_name)
    assert violations, "verifier passed a broken exchange depth"
    text = "\n".join(str(v) for v in violations)
    assert combo_name in text  # names the kernel
    assert any(v.axis == 0 for v in violations)  # names the axis
    k, G = stepper.steps_per_exchange, stepper.halo
    assert str(k * G) in text and str(k * G + 1) in text  # names depths


def test_injected_thin_shard_fails():
    """A shard too thin to serve the deep exchange is caught before
    any program would trace (the halo.exchange_ghosts guard, proven
    statically)."""
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2]"
    )
    stepper = combo.build()
    stepper.interior_shape = (stepper.exchange_depth - 1,) + tuple(
        stepper.interior_shape[1:]
    )
    violations = halo_verify.verify_stepper(stepper)
    assert any("serve the exchange" in v.what for v in violations)


def test_stencil_spec_is_queryable_metadata():
    """Satellite: the R=3-style constants are promoted to one
    queryable contract shared by every rung."""
    for combo in halo_verify.default_combos():
        try:
            stepper = combo.build()
        except ValueError:
            continue
        spec = stepper.stencil_spec()
        for key in ("kernel", "stage_radius", "fused_stages",
                    "ghost_depth", "exchange_depth",
                    "steps_per_exchange"):
            assert key in spec, (combo.name, key)
        assert spec["stage_radius"] >= 1
        assert spec["ghost_depth"] >= (
            spec["fused_stages"] * spec["stage_radius"]
        )


# --------------------------------------------------------------------- #
# Collective-schedule & SPMD consistency verifier (ISSUE 12)
# --------------------------------------------------------------------- #
def test_collective_tree_proves_rank_uniform():
    """The whole installed package is proven: no duplicate rendezvous
    tags, no rank-divergent joins, no undeclared/stale collective
    metadata, no unreachable rendezvous, all sharding cases clean —
    and the extraction actually saw the distributed layer (barriers,
    agrees, ppermutes, reductions and shard_map entries all present)."""
    report = collective_verify.verify_tree()
    assert report.ok, "\n".join(str(v) for v in report.violations)
    kinds = {s.kind for s in report.sites}
    assert {"barrier", "agree", "ppermute", "reduce",
            "shard_map"} <= kinds, kinds
    assert len(report.cases_proven) >= 7
    assert report.reachable_functions > 0


def test_rank_guarded_collective_and_effect_pragmas_audited():
    """Every rank-divergent site in the shipped tree carries the
    audited allow-pragma (satellite 1) — the lint rules run in the
    package-wide clean gate above, so here just pin that the rules ARE
    registered and the audited sites exist."""
    rules = all_rules()
    assert "rank-divergent-collective" in rules
    assert "rank-divergent-effect" in rules
    # the commit protocol's single-writer sites carry the audit
    with open(os.path.join(PKG, "utils", "io.py")) as f:
        io_src = f.read()
    assert io_src.count("allow[rank-divergent-effect]") >= 2


def test_static_schedule_extracts_commit_chain():
    """The checkpoint-commit protocol's three barriers extract as one
    ordered chain, and the supervisor's agree tags land in the
    alphabet — what the dynamic cross-check matches streams against."""
    sched = collective_verify.static_schedule()
    tags = {(t.kind, t.template) for t in sched.alphabet}
    for want in (("agree", "checkpoint"), ("agree", "rollback"),
                 ("barrier", "ckptd-begin:*"),
                 ("barrier", "ckptd-shards:*"),
                 ("barrier", "ckptd-commit:*")):
        assert want in tags, (want, tags)
    chains = [[t.template for t in c] for c in sched.chains]
    assert ["ckptd-begin:*", "ckptd-shards:*",
            "ckptd-commit:*"] in chains, chains


def test_collective_metadata_drift_guard_both_directions():
    """The issuing layers' declared tag namespaces equal the extracted
    call sites exactly (the stencil_spec discipline applied to
    collectives): drop a declaration or add an undeclared tag and
    verify_tree trips."""
    from multigpu_advectiondiffusion_tpu.parallel.multihost import (
        collective_spec,
    )

    spec = collective_spec()
    sched = collective_verify.static_schedule()
    extracted_barriers = {
        t.template for t in sched.alphabet if t.kind == "barrier"
    }
    extracted_agrees = {
        t.template for t in sched.alphabet if t.kind == "agree"
    }
    assert extracted_barriers == set(spec["barrier"])
    assert extracted_agrees == set(spec["agree"])


def test_seeded_duplicate_tag_and_divergent_join_fail_loudly():
    with tempfile.TemporaryDirectory() as d:
        atomic_write_text(
            os.path.join(d, "a.py"),
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n\n"
            "def one():\n"
            "    multihost.barrier('tag-x')\n",
        )
        atomic_write_text(
            os.path.join(d, "b.py"),
            "import jax\n"
            "from multigpu_advectiondiffusion_tpu.parallel import "
            "multihost\n\n"
            "def two():\n"
            "    multihost.barrier('tag-x')\n"
            "\n"
            "def three():\n"
            "    if jax.process_index() == 0:\n"
            "        multihost.barrier('coord-only')\n",
        )
        report = collective_verify.verify_tree(root=d)
    rules = {v.rule for v in report.violations}
    assert "duplicate-collective-tag" in rules
    assert "divergent-join" in rules
    dup = next(v for v in report.violations
               if v.rule == "duplicate-collective-tag")
    assert "tag-x" in dup.site and dup.line > 0  # names file/line/tag
    join = next(v for v in report.violations
                if v.rule == "divergent-join")
    assert "process_index" in join.site


def test_sharding_pass_catches_bad_spec_and_member_in_spatial():
    cases = [
        collective_verify.ShardingCase(
            "bad-axis", {"dz": 2}, {0: "zd"}),
        collective_verify.ShardingCase(
            "member-in-spatial", {"members": 4, "dz": 2},
            {0: "members"}, member=True),
        collective_verify.ShardingCase(
            "double-duty-axis", {"dz": 2}, {0: "dz", 1: "dz"}),
    ]
    proven, violations = collective_verify.verify_sharding_cases(cases)
    assert not proven
    by_case = {v.path for v in violations}
    assert by_case == {"bad-axis", "member-in-spatial",
                       "double-duty-axis"}
    texts = "\n".join(v.message for v in violations)
    assert "missing mesh" in texts
    assert "may not shard a grid axis" in texts
    assert "two grid axes" in texts


def test_member_mesh_rides_the_registry_pass():
    """halo_verify.verify_member_mesh now delegates to the ONE
    registry-driven mesh-layout checker — same verdicts as before."""
    res = halo_verify.verify_member_mesh(
        "ok", {"members": 4, "dz": 2}, {0: "dz"}
    )
    assert not res.violations
    res = halo_verify.verify_member_mesh(
        "missing-members", {"dz": 2}, {0: "dz"}
    )
    assert any("members axis" in v.what for v in res.violations)


def test_remote_dma_declaration_is_validated():
    """Satellite: the ROADMAP item 2 in-kernel exchange contract,
    landed ahead of the kernel — a consistent window passes, every
    inconsistency is named."""
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2]"
    )
    stepper = combo.build()
    assert stepper.stencil_spec()["remote_dma"] is None  # empty today
    depth = stepper.exchange_depth
    stepper.remote_dma = {"axis": 0, "window_rows": depth,
                          "buffers": 2}
    assert not halo_verify.verify_stepper(stepper, kernel=combo.name)
    stepper.remote_dma = {"axis": 1, "window_rows": depth + 1,
                          "buffers": 1}
    violations = halo_verify.verify_stepper(stepper, kernel=combo.name)
    text = "\n".join(str(v) for v in violations)
    assert "slab decomposition axis" in text
    assert "disagrees with the exchange depth" in text
    assert "double-buffered" in text
    stepper.remote_dma = {"axis": 0}
    violations = halo_verify.verify_stepper(stepper, kernel=combo.name)
    assert any("missing fields" in v.what for v in violations)


def test_remote_dma_on_unsharded_stepper_declines():
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[unsharded]"
    )
    stepper = combo.build()
    stepper.remote_dma = {
        "axis": 0, "window_rows": stepper.exchange_depth, "buffers": 2,
    }
    violations = halo_verify.verify_stepper(stepper)
    assert any("no neighbor" in v.what for v in violations)


def test_remote_dma_disjointness_and_semaphore_pairing():
    """The shipped dma rung's full declaration proves clean; an
    injected overlapping recv window (push landing over the receiver's
    core — the silent-corruption race), an out-of-core send window and
    an unpaired semaphore set are each rejected, named."""
    combo = next(
        c for c in halo_verify.default_combos()
        if c.name == "slab-diffusion[k=2,dma]"
    )
    stepper = combo.build()
    assert not halo_verify.verify_stepper(stepper, kernel=combo.name)
    spec = stepper.stencil_spec()
    assert spec["exchange"] == "dma"
    assert spec["remote_dma"]["buffers"] >= 2
    depth = stepper.exchange_depth
    pz = stepper.padded_shape[0]

    stepper.remote_dma = dict(spec["remote_dma"])
    stepper.remote_dma["recv_windows"] = (
        (depth, 2 * depth), (pz - depth, pz),
    )
    v = halo_verify.verify_stepper(stepper, kernel=combo.name)
    assert any("overlaps the receiver's core" in x.what for x in v)

    stepper.remote_dma = dict(spec["remote_dma"])
    stepper.remote_dma["send_windows"] = (
        (0, depth), (pz - 2 * depth, pz - depth),
    )
    v = halo_verify.verify_stepper(stepper, kernel=combo.name)
    assert any("outside the shard's own core" in x.what for x in v)

    stepper.remote_dma = dict(spec["remote_dma"])
    stepper.remote_dma["semaphores"] = ("send",)
    v = halo_verify.verify_stepper(stepper, kernel=combo.name)
    assert any("pair a send and a recv" in x.what for x in v)


def test_collective_registry_knows_the_dma_rung():
    """The dma rung replaces the ppermute site: its kernel sites are
    extracted as ``remote_dma`` collectives, the declared transport
    metadata (multihost.collective_spec <- halo.remote_dma_spec)
    matches both directions, and the dynamic counter profile reads the
    dma counters — no stale-ppermute false positive on a dma stream."""
    report = collective_verify.verify_tree()
    assert report.ok
    assert any(s.kind == "remote_dma" for s in report.sites)
    assert "slab[dz=2,exchange=dma]" in report.cases_proven
    prof = collective_verify.halo_counter_profile([
        {"kind": "counter", "name": "halo.dma_bytes_per_execution",
         "axis": 0, "mesh_axis": "dz", "total": 1024},
    ])
    assert prof == {("halo.dma_bytes_per_execution", 0, "dz"): 1}


def test_verify_trace_accepts_linearization_and_rejects_drift():
    sched = collective_verify.static_schedule()
    good = [
        ("barrier", "ckptd-begin:/run/checkpoint_000025.ckptd"),
        ("barrier", "ckptd-shards:/run/checkpoint_000025.ckptd"),
        ("barrier", "ckptd-commit:/run/checkpoint_000025.ckptd"),
        ("agree", "checkpoint"),
        ("barrier", "ckptd-begin:/run/checkpoint_000050.ckptd"),
        ("barrier", "ckptd-shards:/run/checkpoint_000050.ckptd"),
        ("barrier", "ckptd-commit:/run/checkpoint_000050.ckptd"),
    ]
    assert collective_verify.verify_trace(
        {0: good, 1: list(good)}, sched
    ) == []
    # an unknown rendezvous tag is schema drift
    problems = collective_verify.verify_trace(
        {0: good + [("barrier", "made-up-tag")]}, sched
    )
    assert any("matches no statically extracted" in p
               for p in problems)
    # a commit landing before its shards is a broken protocol
    reordered = [good[0], good[2], good[1]] + good[3:]
    problems = collective_verify.verify_trace(
        {0: reordered, 1: reordered}, sched
    )
    assert any("out of order" in p for p in problems)
    # rank-divergent sequences are the deadlock observed
    problems = collective_verify.verify_trace(
        {0: good, 1: good[:-1]}, sched
    )
    assert any("divergent collective sequences" in p for p in problems)


def test_collective_sequence_and_halo_profile_projection():
    events = [
        {"kind": "sync", "name": "barrier", "tag": "ckptd-begin:/d"},
        {"kind": "resilience", "name": "agree", "tag": "checkpoint"},
        {"kind": "physics", "name": "probe", "step": 1},
        {"kind": "counter", "name": "halo.exchanges_traced",
         "axis": 0, "mesh_axis": "dz"},
        {"kind": "counter", "name": "tune.lookups", "axis": 0},
    ]
    assert collective_verify.collective_sequence(events) == [
        ("barrier", "ckptd-begin:/d"), ("agree", "checkpoint"),
    ]
    prof = collective_verify.halo_counter_profile(events)
    assert prof == {("halo.exchanges_traced", 0, "dz"): 1}


# --------------------------------------------------------------------- #
# Checkify sanitizer
# --------------------------------------------------------------------- #
@pytest.fixture
def checkified():
    sanitizer.configure(enabled=True, errors=("nan", "div", "oob"))
    try:
        yield
    finally:
        sanitizer.configure(enabled=False)


def _nan_solver():
    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models.diffusion import (
        DiffusionConfig,
        DiffusionSolver,
    )

    grid = Grid.make(12, 10, 8, lengths=2.0)

    def nan_source(u):
        # one poisoned cell: the sentinel sees it only at the next
        # norm probe; checkify sees the producing primitive
        return jnp.zeros_like(u).at[2, 2, 2].set(jnp.nan)

    return DiffusionSolver(DiffusionConfig(grid=grid, source=nan_source))


def test_checkify_catches_injected_nan_named_before_sentinel(checkified):
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        SanitizerError,
    )
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    solver = _nan_solver()
    with pytest.raises(SanitizerError) as exc:
        supervise_run(solver, solver.initial_state(), iters=8,
                      sentinel_every=4, max_retries=1)
    # named: checkify's message carries the offending primitive
    assert "nan" in str(exc.value).lower()
    assert "primitive" in exc.value.checkify_message
    # located: the supervisor pinned the dispatch-time error to a step
    assert exc.value.step >= 0


def test_same_fault_without_checkify_is_a_plain_divergence():
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        SanitizerError,
        SolverDivergedError,
    )
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    solver = _nan_solver()
    with pytest.raises(SolverDivergedError) as exc:
        supervise_run(solver, solver.initial_state(), iters=8,
                      sentinel_every=4, max_retries=0)
    assert not isinstance(exc.value, SanitizerError)


def test_checkify_rollback_event_rides_supervisor_path(checkified):
    """The sanitizer is the rollback trigger, not a new recovery
    mechanism: the retry ledger shows the checkify reason."""
    from multigpu_advectiondiffusion_tpu.resilience.errors import (
        SanitizerError,
    )
    from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
        supervise_run,
    )

    solver = _nan_solver()
    try:
        supervise_run(solver, solver.initial_state(), iters=8,
                      sentinel_every=4, max_retries=2)
    except SanitizerError as err:
        assert "checkify" in err.reason


def test_checkify_clean_run_matches_unchecked(checkified):
    """Instrumentation must not perturb the physics: a healthy run
    under --checkify reproduces the unchecked trajectory bit-exact."""
    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models.diffusion import (
        DiffusionConfig,
        DiffusionSolver,
    )

    grid = Grid.make(10, 8, 6, lengths=2.0)
    cfg = DiffusionConfig(grid=grid)
    checked = DiffusionSolver(cfg)
    out_checked = checked.run(checked.initial_state(), 5)
    sanitizer.configure(enabled=False)
    plain = DiffusionSolver(cfg)
    out_plain = plain.run(plain.initial_state(), 5)
    assert jnp.array_equal(out_checked.u, out_plain.u)


def test_checkify_declines_meshes_loudly(checkified, devices):
    import jax

    from multigpu_advectiondiffusion_tpu.core.grid import Grid
    from multigpu_advectiondiffusion_tpu.models.diffusion import (
        DiffusionConfig,
        DiffusionSolver,
    )
    from multigpu_advectiondiffusion_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dz": 2}, devices=jax.devices()[:2])
    grid = Grid.make(8, 8, 8, lengths=2.0)
    solver = DiffusionSolver(DiffusionConfig(grid=grid), mesh=mesh)
    with pytest.raises(ValueError, match="checkify"):
        solver.run(solver.initial_state(), 1)


def test_sanitizer_configure_validates():
    with pytest.raises(ValueError):
        sanitizer.configure(errors=("nan", "nonsense"))
    with pytest.raises(ValueError):
        sanitizer.configure(errors=())
    assert not sanitizer.enabled()


# --------------------------------------------------------------------- #
# CLI + gate surfaces
# --------------------------------------------------------------------- #
def test_check_cli_clean_and_selftest():
    from multigpu_advectiondiffusion_tpu.analysis import cli as check_cli

    assert check_cli.main([]) == 0
    assert check_cli.main(["--selftest"]) == 0
    assert check_cli.main(["--list-rules"]) == 0


def test_check_cli_flags_a_seeded_tree():
    from multigpu_advectiondiffusion_tpu.analysis import cli as check_cli

    with tempfile.TemporaryDirectory() as d:
        atomic_write_text(
            os.path.join(d, "bad.py"),
            RULE_FIXTURES["raw-artifact-write"]["bad"],
        )
        assert check_cli.main(["--root", d, "--skip-halo"]) == 1


def test_atomic_write_text_publishes_complete_files(tmp_path):
    path = str(tmp_path / "artifact.json")
    atomic_write_text(path, "first")
    atomic_write_text(path, "second")
    with open(path) as f:
        assert f.read() == "second"
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
