"""Event-schema drift guard (ISSUE 6 satellite).

The registry (telemetry/schema.py) is the single source of truth for
event kinds/names/required fields. These tests hold three things to it:

* every emission site in the package source (statically scanned);
* README's Observability event table (both directions);
* real emitted events (structural validation of a live stream).

Someone adding a ``telemetry.event("newkind", ...)`` call — or a new
README row — without registering it fails tier-1 here, not in a
downstream consumer six months later.
"""

from __future__ import annotations

import json
import os
import re

from multigpu_advectiondiffusion_tpu import telemetry
from multigpu_advectiondiffusion_tpu.telemetry import schema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "multigpu_advectiondiffusion_tpu")


def test_every_emitted_event_is_registered():
    pairs, counters = schema.scan_emitted(PKG)
    assert pairs, "the static scan found no emission sites at all?"
    unregistered = sorted(
        f"{kind}:{name}" for kind, name in pairs
        if not schema.registered(kind, name)
    )
    assert not unregistered, (
        "emission sites not covered by telemetry/schema.py "
        f"EVENT_REGISTRY: {unregistered} — register the kind/name "
        "(and document it in README's event table)"
    )
    unknown_counters = sorted(counters - schema.COUNTER_NAMES)
    assert not unknown_counters, (
        f"counters missing from schema.COUNTER_NAMES: {unknown_counters}"
    )


def _readme_kinds() -> set:
    with open(os.path.join(REPO, "README.md")) as f:
        text = f.read()
    start = text.index("## Observability")
    end = text.index("## ", start + 4)
    section = text[start:end]
    return set(re.findall(r"^\s*\|\s*`([a-z_]+)`", section, re.M))


def test_readme_event_table_matches_registry():
    readme = _readme_kinds()
    registry = set(schema.EVENT_REGISTRY)
    missing_from_readme = sorted(registry - readme)
    assert not missing_from_readme, (
        "event kinds registered but absent from README's Observability "
        f"table: {missing_from_readme}"
    )
    unregistered_in_readme = sorted(readme - registry)
    assert not unregistered_in_readme, (
        "README documents event kinds the registry does not know: "
        f"{unregistered_in_readme}"
    )


def test_validate_event_passes_real_stream(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path) as sink:
        with sink.span("run_solver", run="x"):
            sink.counter("halo.exchanges_traced", 1, axis=0)
            sink.event("physics", "probe", step=1, time=0.1)
            sink.event("progress", "chunk", step=1, steps_done=1,
                       step_seconds=0.01)
    for line in open(path):
        ev = json.loads(line)
        assert schema.validate_event(ev) == [], (ev,
                                                 schema.validate_event(ev))


def test_validate_event_flags_drift():
    assert any(
        "unregistered kind" in p
        for p in schema.validate_event(
            {"t": 0, "proc": 0, "kind": "madeup", "name": "x"}
        )
    )
    assert any(
        "unregistered name" in p
        for p in schema.validate_event(
            {"t": 0, "proc": 0, "kind": "physics", "name": "nope"}
        )
    )
    assert any(
        "missing field" in p
        for p in schema.validate_event(
            {"t": 0, "proc": 0, "kind": "physics", "name": "probe"}
        )
    )
    assert any(
        "envelope" in p
        for p in schema.validate_event({"kind": "meta", "name": "open"})
    )
