"""Fleet metrics, SLO burn-rate alerting, and the status surface
(ISSUE 18).

The properties the tentpole rests on, each held directly:

* fixed log-boundary histograms merge EXACTLY (elementwise bucket
  adds; merged == one histogram that saw everything), and the
  quantile estimator honors its documented worst-case relative error
  bound (one bucket's width);
* two processes' snapshots union into one fleet view — counters and
  buckets add, gauges take newest value + running max;
* a snapshot directory survives a SIGKILL between writes: the
  previously published file stays parseable (atomic replace) and a
  corrupt sibling snapshot is skipped, never fatal;
* the SLO burn-rate engine fires on a synthetic deadline-miss stream
  and stays silent on a healthy one, with alert/resolve hysteresis;
* the replay adapter derives EXACTLY the counters the live
  instruments counted, from the server's own event stream — the
  exactly-once reconciliation the metrics gate automates end to end;
* the discovery fix: a service root's per-job streams under
  ``jobs/<id>/`` are found by ``load_streams``;
* ``tpucfd-status --once --json`` reports a populated frame.
"""

from __future__ import annotations

import json
import math
import os
import random

import pytest

from multigpu_advectiondiffusion_tpu.telemetry import metrics as M


# --------------------------------------------------------------------- #
# Histogram: exact merge + bounded quantile error
# --------------------------------------------------------------------- #
def test_histogram_bucket_merge_is_exact():
    random.seed(7)
    xs = [random.lognormvariate(-4.0, 2.5) for _ in range(4000)]
    parts = [M.Histogram("h") for _ in range(3)]
    union = M.Histogram("h")
    for i, x in enumerate(xs):
        parts[i % 3].observe(x)
        union.observe(x)
    merged = M.Histogram("h")
    for p in parts:
        merged.merge(p)
    # bucket-level identity, not approximate agreement
    assert merged.counts == union.counts
    assert merged.count == union.count == len(xs)
    assert math.isclose(merged.sum, union.sum, rel_tol=1e-12)
    assert merged.min == union.min and merged.max == union.max


def test_histogram_merge_is_order_independent():
    a, b = M.Histogram("h"), M.Histogram("h")
    for x in (0.001, 0.5, 30.0):
        a.observe(x)
    for x in (0.002, 7.0):
        b.observe(x)
    ab, ba = M.Histogram("h"), M.Histogram("h")
    ab.merge(a), ab.merge(b)
    ba.merge(b), ba.merge(a)
    assert ab.counts == ba.counts and ab.sum == ba.sum


def test_histogram_refuses_incompatible_bounds():
    a = M.Histogram("h")
    b = M.Histogram("h", bounds=(1.0, 2.0, 4.0))
    with pytest.raises(ValueError, match="incompatible"):
        a.merge(b)


def test_quantile_honors_documented_error_bound():
    random.seed(11)
    xs = sorted(random.lognormvariate(-2.0, 1.7) for _ in range(6000))
    h = M.Histogram("h")
    for x in xs:
        h.observe(x)
    for q in (0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 0.999):
        exact = xs[int(q * (len(xs) - 1))]
        est = h.quantile(q)
        rel = abs(est - exact) / exact
        assert rel <= M.QUANTILE_REL_ERROR + 1e-9, (q, est, exact, rel)


def test_quantile_edge_cases():
    h = M.Histogram("h")
    assert h.quantile(0.5) is None and h.mean() is None
    h.observe(0.25)
    # one observation: every quantile is clamped to [min, max] = it
    assert h.quantile(0.0) == h.quantile(0.5) == h.quantile(1.0) == 0.25
    h.observe(float("nan"))  # refused, not bucketed
    assert h.count == 1


def test_overflow_and_underflow_buckets():
    h = M.Histogram("h")
    h.observe(1e-9)   # below the lowest bound
    h.observe(1e9)    # above the highest
    assert h.counts[0] == 1 and h.counts[-1] == 1
    assert h.count == 2
    # quantiles stay inside the observed range even out-of-bounds
    assert 1e-9 <= h.quantile(0.0) <= 1e9
    assert 1e-9 <= h.quantile(1.0) <= 1e9


# --------------------------------------------------------------------- #
# Snapshots: two-process merge, Prometheus text, crash safety
# --------------------------------------------------------------------- #
def _two_registries():
    r1 = M.MetricsRegistry(proc="rank0")
    r2 = M.MetricsRegistry(proc="rank1")
    for r, k in ((r1, 3), (r2, 4)):
        r.counter("reqs_total").inc(k)
        for i in range(k):
            r.histogram("lat_seconds").observe(0.01 * (i + 1))
    r1.gauge("depth").set(5)
    r2.gauge("depth").set(2)
    return r1, r2


def test_two_process_snapshot_merge(tmp_path):
    r1, r2 = _two_registries()
    s1 = r1.snapshot()
    s2 = r2.snapshot()
    s2["wall_time"] = s1["wall_time"] + 10.0  # rank1 published later
    merged = M.merge_snapshots([s1, s2])
    assert merged["counters"]["reqs_total"] == 7
    hist = M.snapshot_histogram(merged, "lat_seconds")
    assert hist.count == 7
    # gauge: newest value wins, max is the max across processes
    assert merged["gauges"]["depth"]["value"] == 2
    assert merged["gauges"]["depth"]["max"] == 5
    assert sorted(merged["merged_procs"]) == ["rank0", "rank1"]


def test_merge_snapshot_dirs_unions_processes(tmp_path):
    root = str(tmp_path / "metrics")
    r1, r2 = _two_registries()
    r1.write_snapshot(os.path.join(root, r1.proc))
    r2.write_snapshot(os.path.join(root, r2.proc))
    merged = M.merge_snapshot_dirs(root)
    assert merged["snapshots"] == 2 and not merged["skipped"]
    assert merged["counters"]["reqs_total"] == 7


def test_prometheus_text_parses_and_is_cumulative(tmp_path):
    r1, _ = _two_registries()
    d = str(tmp_path / "m")
    r1.write_snapshot(d)
    text = open(os.path.join(d, "metrics.prom")).read()
    samples = M.parse_prometheus(text)
    assert samples["tpucfd_reqs_total"] == 3
    assert samples["tpucfd_lat_seconds_count"] == 3
    # bucket samples are cumulative and end at +Inf == count
    buckets = [v for k, v in samples.items()
               if k.startswith("tpucfd_lat_seconds_bucket")]
    assert buckets == sorted(buckets)
    assert samples['tpucfd_lat_seconds_bucket{le="+Inf"}'] == 3


def test_kill_between_snapshot_writes_leaves_last_valid(tmp_path):
    """The SIGKILL-between-writes contract: write_snapshot goes through
    atomic_write_text, so a death mid-publish leaves (a) the previous
    metrics.json intact and (b) at worst an orphan ``.tmp`` — and a
    snapshot file that IS half-written (simulated corruption) is
    skipped by the merge, never fatal."""
    root = str(tmp_path / "metrics")
    r = M.MetricsRegistry(proc="server-1")
    r.counter("reqs_total").inc(2)
    d = os.path.join(root, r.proc)
    r.write_snapshot(d)
    before = open(os.path.join(d, "metrics.json")).read()
    # a dying process's orphan temp file next to the published snapshot
    with open(os.path.join(d, ".metrics.json.killed.tmp"), "w") as f:
        f.write('{"schema": 1, "counters": {"reqs_tot')
    # previous snapshot still parses bit-for-bit
    assert json.loads(before)["counters"]["reqs_total"] == 2
    merged = M.merge_snapshot_dirs(root)
    assert merged["counters"]["reqs_total"] == 2
    # a sibling incarnation died INSIDE os.replace's window leaving a
    # truncated metrics.json: skipped + reported, not fatal
    bad = os.path.join(root, "server-2")
    os.makedirs(bad)
    with open(os.path.join(bad, "metrics.json"), "w") as f:
        f.write('{"counters": {"reqs_total": 99')
    merged = M.merge_snapshot_dirs(root)
    assert merged["counters"]["reqs_total"] == 2
    assert merged["snapshots"] == 1 and len(merged["skipped"]) == 1


def test_corrupt_snapshot_raises_on_direct_load(tmp_path):
    p = str(tmp_path / "metrics.json")
    with open(p, "w") as f:
        f.write("{not json")
    with pytest.raises(ValueError):
        M.load_snapshot(p)
    with open(p, "w") as f:
        f.write('{"no_counters": 1}')
    with pytest.raises(ValueError, match="not a metrics snapshot"):
        M.load_snapshot(p)


# --------------------------------------------------------------------- #
# SLO burn-rate engine
# --------------------------------------------------------------------- #
WINDOWS = ((60.0, 2.0, 4), (600.0, 1.0, 8))


def _verdict_stream(n, miss, t0=1000.0):
    out = []
    for i in range(n):
        ok_seconds = 5.0 if miss else 0.01
        out.append({
            "kind": "req", "name": "done", "job": f"r{i}",
            "seconds": ok_seconds, "deadline_s": 1.0,
            "slices": 1, "t": t0 + float(i),
        })
    return out


def test_slo_alert_fires_on_deadline_miss_stream():
    verdict = M.evaluate_slo_stream(
        _verdict_stream(12, miss=True), objective=0.99, windows=WINDOWS
    )
    assert verdict["alerts"], verdict
    alert = verdict["alerts"][0]
    assert alert["burn_rate"] > alert["threshold"]
    assert verdict["firing"]


def test_slo_silent_on_healthy_stream():
    verdict = M.evaluate_slo_stream(
        _verdict_stream(12, miss=False), objective=0.99,
        windows=WINDOWS,
    )
    assert not verdict["alerts"]
    assert not verdict["firing"]


def test_slo_hysteresis_one_alert_then_resolve():
    emitted = []
    t = M.SloTracker(objective=0.99, windows=((60.0, 2.0, 4),),
                     emit=lambda name, p: emitted.append(name))
    now = 5000.0
    for i in range(10):  # sustained misses: exactly ONE alert
        t.observe(False, wall=now + i)
        t.evaluate(now=now + i)
    assert emitted == ["alert"]
    # the window drains with time alone -> one resolve
    t.evaluate(now=now + 500.0)
    assert emitted == ["alert", "resolve"]


def test_slo_min_count_suppresses_single_early_miss():
    t = M.SloTracker(objective=0.99, windows=((60.0, 2.0, 4),))
    t.observe(False, wall=100.0)
    assert t.evaluate(now=100.0) == []
    assert not t.firing


# --------------------------------------------------------------------- #
# Replay adapter: exactly-once vs the live instruments
# --------------------------------------------------------------------- #
def _serve_round(root, rids, deadline=None):
    from multigpu_advectiondiffusion_tpu.service.requests import (
        RequestSpec,
        submit_request_to_spool,
    )
    from multigpu_advectiondiffusion_tpu.service.server import (
        RequestServer,
    )

    for i, rid in enumerate(rids):
        submit_request_to_spool(root, RequestSpec(
            request_id=rid, model="diffusion", n=[12, 12],
            t_end=0.18, ic="gaussian",
            ic_params={"width": 0.08 + 0.01 * i},
            deadline_s=deadline,
        ))
    srv = RequestServer(root, max_batch=4, slice_steps=4, fsync=False,
                        metrics_every_s=0.0)
    srv.serve(until_idle=True, poll_seconds=0.001)
    srv.close()
    return srv


def test_replay_counters_match_instrumented_exactly_once(tmp_path):
    root = str(tmp_path / "serve")
    srv = _serve_round(root, ["a", "b", "c"], deadline=300.0)
    live = {k: c.value for k, c in srv.metrics.counters.items()}
    # replay the server's own stream through the adapter
    replayed = M.registry_from_streams([root])
    derived = {k: c.value for k, c in replayed.counters.items()}
    shared = set(live) & set(derived)
    assert "serve_requests_done_total" in shared
    assert "serve_requests_received_total" in shared
    for key in sorted(shared):
        assert derived[key] == live[key], (key, derived, live)
    assert derived["serve_requests_done_total"] == 3
    assert derived["serve_deadline_met_total"] == 3
    # and the published snapshot dir agrees with both
    merged = M.merge_snapshot_dirs(os.path.join(root, "metrics"))
    for key in sorted(shared):
        assert merged["counters"].get(key, 0) == live[key]
    # latency histogram: replay observed the same events
    lat = M.snapshot_histogram(merged, "serve_request_latency_seconds")
    assert lat.count == 3
    assert replayed.histograms[
        "serve_request_latency_seconds"
    ].counts == lat.counts


def test_status_once_json_populated(tmp_path, capsys):
    from multigpu_advectiondiffusion_tpu.cli import status as status_cli

    root = str(tmp_path / "serve")
    _serve_round(root, ["a", "b"], deadline=300.0)
    status_cli.main(["--root", root, "--once", "--json"])
    frame = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert frame["requests"].get("done") == 2
    assert frame["metrics"]["snapshots"] >= 1
    counters = frame["metrics"]["counters"]
    assert counters["serve_requests_done_total"] == 2
    assert "serve_request_latency_seconds" in frame["quantiles"]
    assert not frame["slo"]["firing"]


def test_status_render_text_lines(tmp_path):
    from multigpu_advectiondiffusion_tpu.cli import status as status_cli

    # a bare root (no journal, no snapshots) still renders a frame
    frame = status_cli.collect_status(str(tmp_path))
    lines = status_cli.render_text(frame)
    assert any("tpucfd-status" in ln for ln in lines)
    assert any("slo" in ln for ln in lines)


# --------------------------------------------------------------------- #
# Stream discovery (satellite: analyze.py service roots)
# --------------------------------------------------------------------- #
def test_load_streams_discovers_per_job_streams(tmp_path):
    from multigpu_advectiondiffusion_tpu.telemetry.analyze import (
        discover_streams,
        load_streams,
    )

    root = str(tmp_path)
    ev = {"t": 0.1, "proc": 0, "kind": "progress", "name": "chunk",
          "step": 1, "steps_done": 1, "step_seconds": 0.1}

    def _write(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(ev) + "\n")

    _write(os.path.join(root, "sched_events.jsonl"))
    _write(os.path.join(root, "jobs", "j1", "events.jsonl"))
    _write(os.path.join(root, "jobs", "j2", "events.jsonl"))
    # a rotated segment must ride along, not appear as its own stream
    _write(os.path.join(root, "jobs", "j1", "events.jsonl.1"))
    found = discover_streams(root)
    assert len(found) == 3
    streams = load_streams([root])
    assert len(streams) == 3
    j1 = [s for s in streams if os.sep + "j1" + os.sep in s.path]
    assert len(j1) == 1 and len(j1[0].events) == 2  # .1 prepended


def test_journal_commit_timing_hook(tmp_path):
    from multigpu_advectiondiffusion_tpu.service.journal import Journal

    j = Journal(str(tmp_path / "j.jsonl"), fsync=True)
    h = M.Histogram("fsync")
    j.on_commit_seconds = h.observe
    j.append("note", note="x")
    j.append("note", note="y")
    j.close()
    assert h.count == 2
    assert j.last_commit_seconds is not None
