"""Fault-injection resilience suite (`faults` marker, tier-1, CPU-only).

Every failure mode the resilience subsystem claims to survive is
injected here through ``resilience/faults.py`` and the recovery proven
end-to-end: NaN divergence -> rollback + dt-backoff retry reproducing
the un-faulted answer; Mosaic dispatch failure -> kernel-ladder
degradation (auto completes on XLA with the downgrade recorded, pins
fail loudly); checkpoint corruption/truncation -> ``--resume auto``
skips to the previous CRC-valid file; shard-level corruption -> errors
naming the exact shard and global offsets; SIGTERM -> final CRC-valid
checkpoint + manifest + exit code 75 (subprocess-tested).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
)
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.parallel.mesh import Decomposition
from multigpu_advectiondiffusion_tpu.resilience import (
    EXIT_PREEMPTED,
    DivergenceSentinel,
    PreemptionGuard,
    SimulatedMosaicError,
    SolverDivergedError,
    faults,
    find_latest_checkpoint,
    supervise_run,
)
from multigpu_advectiondiffusion_tpu.utils import io as io_utils
from multigpu_advectiondiffusion_tpu.utils.io import load_binary

pytestmark = pytest.mark.faults


def _diffusion2d(**kw):
    cfg = DiffusionConfig(
        grid=Grid.make(16, 12, lengths=4.0), dtype="float32", **kw
    )
    return DiffusionSolver(cfg)


# --------------------------------------------------------------------- #
# Divergence sentinel
# --------------------------------------------------------------------- #
def test_sentinel_raises_structured_error():
    solver = _diffusion2d()
    state = solver.initial_state()
    sentinel = DivergenceSentinel(solver, growth=1e3)
    sentinel.arm(state)
    assert sentinel.check(state) > 0.0  # healthy state passes

    bad = type(state)(
        u=state.u.at[4, 4].set(jnp.nan), t=state.t, it=state.it
    )
    with pytest.raises(SolverDivergedError) as ei:
        sentinel.check(bad)
    err = ei.value
    assert err.step == int(state.it)
    assert err.t == pytest.approx(float(state.t))
    assert not np.isfinite(err.norm)
    assert "diverged" in str(err)


def test_sentinel_norm_growth_bound():
    solver = _diffusion2d()
    state = solver.initial_state()
    sentinel = DivergenceSentinel(solver, growth=2.0)
    sentinel.arm(state)
    grown = type(state)(u=state.u * 100.0, t=state.t, it=state.it)
    with pytest.raises(SolverDivergedError, match="growth bound"):
        sentinel.check(grown)


def test_sentinel_is_mesh_aware(devices):
    """The probe's pmax rides the solver's own mesh machinery: a NaN on
    ONE shard must surface in the replicated probe value."""
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:2]), ("dy",))
    cfg = DiffusionConfig(grid=Grid.make(16, 12, lengths=4.0),
                          dtype="float32")
    solver = DiffusionSolver(cfg, mesh=mesh,
                             decomp=Decomposition.of({0: "dy"}))
    state = solver.initial_state()
    sentinel = DivergenceSentinel(solver)
    sentinel.arm(state)
    bad_u = state.u.at[1, 1].set(jnp.nan)  # lives on the first shard
    with pytest.raises(SolverDivergedError):
        sentinel.check(type(state)(u=bad_u, t=state.t, it=state.it))


# --------------------------------------------------------------------- #
# Rollback-and-retry (acceptance a)
# --------------------------------------------------------------------- #
def test_nan_rollback_retry_matches_unfaulted_diffusion():
    baseline = _diffusion2d()
    st = baseline.initial_state()
    t_end = 30 * baseline.dt
    ref = baseline.advance_to(st, t_end)

    solver = _diffusion2d()
    state = solver.initial_state()
    with faults.nan_at_step(solver, 6):  # transient blow-up at step 6
        out, report = supervise_run(
            solver, state, t_end=t_end, sentinel_every=3,
            max_retries=2, dt_backoff=0.5,
        )
    assert report.retries == 1
    assert report.events and report.events[0]["reason"] == "non-finite field"
    assert "dt" in report.events[0]["action"]
    assert float(out.t) == pytest.approx(float(ref.t), rel=1e-6)
    assert bool(jnp.isfinite(out.u).all())
    # halved dt after the rollback: same physics to temporal-error tol
    np.testing.assert_allclose(
        np.asarray(out.u), np.asarray(ref.u), atol=2e-3
    )


def test_nan_rollback_retry_shock_oracle():
    """The shock-physics gate as recovery oracle: after a NaN fault,
    rollback + dt backoff must still land the 1-D Burgers Riemann shock
    within one cell of the exact speed (uL+uR)/2 (same tolerance as
    tests/test_shock.py)."""
    grid = Grid.make(200, lengths=2.0)
    cfg = BurgersConfig(grid=grid, ic="riemann", bc="edge", weno_order=5,
                        adaptive_dt=False, cfl=0.4, dtype="float32")
    solver = BurgersSolver(cfg)
    state = solver.initial_state()
    t_end = 100 * solver.dt
    with faults.nan_at_step(solver, 30):
        out, report = supervise_run(
            solver, state, t_end=t_end, sentinel_every=10,
            max_retries=3, dt_backoff=0.5,
        )
    assert report.retries == 1
    x = np.asarray(grid.coords(0, jnp.float32))
    u = np.asarray(out.u)
    j = int(np.argmax(u < 1.5))
    frac = (u[j - 1] - 1.5) / max(u[j - 1] - u[j], 1e-12)
    x_shock = x[j - 1] + frac * (x[j] - x[j - 1])
    exact = 1.5 * float(out.t)  # (uL+uR)/2 with uL=2, uR=1, x0=0
    assert abs(x_shock - exact) <= grid.spacing[0]


def test_persistent_fault_exhausts_retries():
    solver = _diffusion2d()
    state = solver.initial_state()
    with faults.nan_at_step(solver, 4, once=False):
        with pytest.raises(SolverDivergedError):
            supervise_run(
                solver, state, iters=20, sentinel_every=2,
                max_retries=2, dt_backoff=0.5,
            )


def test_supervised_iters_mode_executes_exact_count():
    solver = _diffusion2d()
    state = solver.initial_state()
    with faults.nan_at_step(solver, 4):
        out, report = supervise_run(
            solver, state, iters=12, sentinel_every=2,
            max_retries=2, dt_backoff=0.5,
        )
    assert int(out.it) == 12
    assert report.retries == 1
    assert bool(jnp.isfinite(out.u).all())


# --------------------------------------------------------------------- #
# Kernel-ladder degradation (acceptance c)
# --------------------------------------------------------------------- #
def test_mosaic_failure_auto_degrades_to_xla():
    """impl='pallas' + simulated Mosaic failure at every fused rung:
    the run completes on XLA and the downgrade chain is recorded in
    engaged_path()['degraded'] (slab -> stage -> xla)."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    assert solver.engaged_path()["stepper"].startswith("fused")
    state = solver.initial_state()
    with faults.mosaic_failure():
        out = solver.run(state, 2)
    assert bool(jnp.isfinite(out.u).all())
    engaged = solver.engaged_path()
    assert engaged["stepper"] == "generic-xla"
    assert engaged["impl"] == "pallas"  # the REQUESTED impl is reported
    chain = [(e["from"], e["to"]) for e in engaged["degraded"]]
    assert chain[-1][1] == "xla"
    assert all("Mosaic" in e["reason"] for e in engaged["degraded"])


def test_mosaic_failure_explicit_pin_raises():
    """An explicit rung pin must fail loudly, not degrade."""
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    for impl in ("pallas_stage", "pallas_slab"):
        solver = DiffusionSolver(
            DiffusionConfig(grid=grid, dtype="float32", impl=impl)
        )
        state = solver.initial_state()
        with faults.mosaic_failure():
            with pytest.raises(SimulatedMosaicError):
                solver.run(state, 2)
        assert not solver._degrade_events


def test_degradation_matches_unfaulted_answer():
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    ref_solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="xla")
    )
    ref = ref_solver.run(ref_solver.initial_state(), 3)
    solver = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )
    with faults.mosaic_failure():
        out = solver.run(solver.initial_state(), 3)
    np.testing.assert_allclose(
        np.asarray(out.u), np.asarray(ref.u), atol=1e-6
    )


def test_unknown_impl_rejected_at_construction():
    with pytest.raises(ValueError, match="unknown impl"):
        DiffusionConfig(grid=Grid.make(8, 8, lengths=2.0), impl="palas")
    with pytest.raises(ValueError, match="unknown impl"):
        BurgersConfig(grid=Grid.make(8, lengths=2.0), impl="cuda")


# --------------------------------------------------------------------- #
# Checkpoint corruption + --resume auto (acceptance b)
# --------------------------------------------------------------------- #
def test_resume_auto_skips_corrupt_newest(tmp_path):
    full = tmp_path / "full"
    run = tmp_path / "run"
    args = ["diffusion2d", "--n", "16", "12"]
    cli_main(args + ["--iters", "12", "--save", str(full)])
    cli_main(args + ["--iters", "8", "--save", str(run),
                     "--checkpoint-every", "2"])
    faults.corrupt_checkpoint(str(run / "checkpoint_000008.ckpt"))
    picked = find_latest_checkpoint(str(run))
    assert picked == str(run / "checkpoint_000006.ckpt")
    # resume auto continues from it=6 -> 6 more iters reproduces the
    # uninterrupted 12-iter run exactly (same fixed-dt trajectory)
    cli_main(args + ["--iters", "6", "--save", str(run),
                     "--resume", "auto"])
    a = load_binary(str(full / "result.bin"), (12, 16))
    b = load_binary(str(run / "result.bin"), (12, 16))
    np.testing.assert_array_equal(a, b)


def test_resume_auto_skips_truncated_and_nonnumeric(tmp_path):
    run = tmp_path / "run"
    cli_main(["diffusion2d", "--n", "16", "12", "--iters", "4",
              "--save", str(run), "--checkpoint-every", "2"])
    faults.truncate_checkpoint(str(run / "checkpoint_000004.ckpt"))
    # a user file must never be auto-selected even when newest
    (run / "checkpoint_best.ckpt").write_bytes(b"not a checkpoint")
    picked = find_latest_checkpoint(str(run))
    assert picked == str(run / "checkpoint_000002.ckpt")


def test_resume_auto_empty_dir_starts_fresh(tmp_path):
    run = tmp_path / "run"
    cli_main(["diffusion2d", "--n", "16", "12", "--iters", "2",
              "--save", str(run), "--resume", "auto"])
    summary = json.loads((run / "summary.json").read_text())
    assert summary["iters"] == 2


def test_verify_checkpoint_catches_truncation(tmp_path):
    run = tmp_path / "run"
    cli_main(["diffusion2d", "--n", "16", "12", "--iters", "2",
              "--save", str(run), "--checkpoint-every", "2"])
    path = str(run / "checkpoint_000002.ckpt")
    io_utils.verify_checkpoint(path)  # pristine passes
    faults.truncate_checkpoint(path, keep_bytes=48)
    with pytest.raises(IOError, match="truncated"):
        io_utils.verify_checkpoint(path)


# --------------------------------------------------------------------- #
# Sharded-checkpoint error reporting (satellite)
# --------------------------------------------------------------------- #
def _sharded_state(devices, tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from multigpu_advectiondiffusion_tpu.models.state import SolverState

    mesh = Mesh(np.asarray(devices[:2]), ("dy",))
    sharding = NamedSharding(mesh, P("dy", None))
    u = jax.device_put(
        jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8), sharding
    )
    state = SolverState(u=u, t=jnp.asarray(0.5), it=jnp.asarray(4))
    d = str(tmp_path / "state.ckptd")
    io_utils.save_checkpoint_sharded(d, state)
    shard_files = sorted(
        n for n in os.listdir(d) if n.startswith("shard_")
    )
    assert len(shard_files) == 2
    return d, shard_files


def test_ckptd_corrupt_shard_names_file_and_offsets(devices, tmp_path):
    d, shard_files = _sharded_state(devices, tmp_path)
    victim = shard_files[-1]  # the z>=8 block
    faults.corrupt_checkpoint(os.path.join(d, victim))
    with pytest.raises(IOError) as ei:
        io_utils.load_checkpoint(d)
    msg = str(ei.value)
    assert victim in msg, "error must name the exact shard file"
    assert "global offsets" in msg and "[8:16)" in msg
    with pytest.raises(IOError, match="global offsets"):
        io_utils.verify_checkpoint(d)


def test_ckptd_missing_shard_lists_absent_offsets(devices, tmp_path):
    d, shard_files = _sharded_state(devices, tmp_path)
    victim = shard_files[0]
    os.remove(os.path.join(d, victim))
    with pytest.raises(IOError) as ei:
        io_utils.load_checkpoint(d)
    msg = str(ei.value)
    assert "missing" in msg and victim in msg
    assert "[0:8)" in msg, "error must list the absent global offsets"


# --------------------------------------------------------------------- #
# Supervisor restart determinism (ISSUE 5 satellite): rollback-retry
# with dt backoff is REPRODUCIBLE — a second supervised run resumed
# from the same checkpoint with the same flags replays the identical
# retry ledger and lands on the bit-identical final state.
# --------------------------------------------------------------------- #
def _fused_diffusion3d():
    # the grid test_mosaic_* proves engages the fused rung
    grid = Grid.make(24, 16, 16, lengths=[4.0, 4.0, 6.0])
    return DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32", impl="pallas")
    )


def test_supervised_restart_determinism_fused_f32(tmp_path):
    seed = _fused_diffusion3d()
    assert seed.engaged_path()["stepper"].startswith("fused")
    pre = seed.run(seed.initial_state(), 6)
    ckpt = str(tmp_path / "c.ckpt")
    io_utils.save_checkpoint(ckpt, pre)

    def resumed_supervised_run():
        solver = _fused_diffusion3d()
        state = io_utils.load_checkpoint(ckpt)
        state = type(state)(
            u=jnp.asarray(state.u, solver.dtype), t=state.t, it=state.it
        )
        with faults.nan_at_step(solver, 10):
            return supervise_run(
                solver, state, iters=12, sentinel_every=2,
                max_retries=3, dt_backoff=0.5,
            )

    out_a, rep_a = resumed_supervised_run()
    out_b, rep_b = resumed_supervised_run()
    assert rep_a.retries == rep_b.retries == 1
    assert rep_a.events == rep_b.events  # identical retry ledger
    assert "dt" in rep_a.events[0]["action"]
    assert int(out_a.it) == int(out_b.it) == 18
    np.testing.assert_array_equal(  # f32 bit-exact on the fused rung
        np.asarray(out_a.u), np.asarray(out_b.u)
    )


# --------------------------------------------------------------------- #
# Preemption (acceptance d)
# --------------------------------------------------------------------- #
def test_preemption_guard_latches_signal():
    with PreemptionGuard(signals=(signal.SIGTERM,)) as guard:
        assert not guard.should_stop
        faults.send_signal()  # SIGTERM to self; handler latches it
        time.sleep(0.01)
        assert guard.should_stop
        assert guard.signum == signal.SIGTERM
    # handlers restored on exit: a fresh guard starts clean
    with PreemptionGuard(signals=(signal.SIGTERM,)) as guard2:
        assert not guard2.should_stop


def test_sigterm_mid_run_checkpoints_and_exits_75(tmp_path):
    """A SIGTERM sent to the CLI mid-run must produce a loadable,
    CRC-valid final checkpoint, a preempt.json manifest, and the
    documented exit code (75) — driven through a real subprocess so the
    whole signal -> chunk-boundary -> atomic-write -> exit path runs."""
    out_dir = tmp_path / "run"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "multigpu_advectiondiffusion_tpu.cli",
         "diffusion2d", "--n", "16", "12", "--iters", "2000000",
         "--save", str(out_dir), "--checkpoint-every", "50",
         "--checkpoint-keep", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            if out_dir.is_dir() and any(
                n.endswith(".ckpt") for n in os.listdir(out_dir)
            ):
                break  # compile finished, chunked loop is running
            if proc.poll() is not None:
                pytest.fail(
                    "CLI exited before any checkpoint: "
                    + proc.stdout.read()[-2000:]
                )
            time.sleep(0.2)
        else:
            pytest.fail("no checkpoint appeared within 120 s")
        proc.send_signal(signal.SIGTERM)
        stdout, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == EXIT_PREEMPTED, stdout[-2000:]

    manifest = json.loads((out_dir / "preempt.json").read_text())
    assert manifest["signal"] == int(signal.SIGTERM)
    assert manifest["exit_code"] == EXIT_PREEMPTED
    ckpt = manifest["checkpoint"]
    io_utils.verify_checkpoint(ckpt)  # CRC-valid
    st = io_utils.load_checkpoint(ckpt)  # and loadable
    assert int(st.it) == manifest["iteration"] > 0
    # the preemption checkpoint is what --resume auto picks up
    assert find_latest_checkpoint(str(out_dir)) == ckpt


# --------------------------------------------------------------------- #
# Supervised CLI summary + distributed-init retry (satellites)
# --------------------------------------------------------------------- #
def test_cli_sentinel_records_resilience_in_summary(tmp_path):
    run = tmp_path / "run"
    cli_main(["diffusion2d", "--n", "16", "12", "--iters", "6",
              "--save", str(run), "--sentinel-every", "2"])
    summary = json.loads((run / "summary.json").read_text())
    res = summary["resilience"]
    assert res["sentinel_every"] == 2
    assert res["probes"] >= 3
    assert res["retries"] == 0 and not res["preempted"]


def test_multihost_initialize_retries_with_backoff(monkeypatch):
    from multigpu_advectiondiffusion_tpu.parallel import multihost

    calls = {"n": 0}

    def flaky(**kwargs):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator not reachable yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    multihost.initialize(
        coordinator_address="localhost:1234", num_processes=1,
        process_id=0, attempts=3, backoff_seconds=0.0,
    )
    assert calls["n"] == 3

    def always_down(**kwargs):
        calls["n"] += 1
        raise RuntimeError("connection refused")

    calls["n"] = 0
    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    with pytest.raises(RuntimeError, match="after 2 attempt"):
        multihost.initialize(
            coordinator_address="localhost:1234", num_processes=1,
            process_id=0, attempts=2, backoff_seconds=0.0,
        )
    assert calls["n"] == 2

    def already(**kwargs):
        raise RuntimeError("jax.distributed is already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", already)
    multihost.initialize(attempts=1)  # idempotent success, no raise
