"""Mesh-scale ensemble serving (ISSUE 11): member-sharded and
members x z-slab batched dispatch on the 8-virtual-device CPU mesh.

Acceptance pins:

* B=8 on a members-only mesh AND on a members x dz=2 mesh is
  bit-exact vs the PR 9 single-device ensemble on diffusion, ulp on
  WENO5;
* the B-folded slab rung (slab pin, members-only mesh) is bit-exact
  against per-member slab runs;
* one diverging member is named by index UNDER SHARDING, the others'
  results stay valid;
* the tuner MEASURES batched candidates at the actual B (no
  single-run proxy), keys by mesh layout, and its ``tune:measure``
  rows carry B;
* a mesh without a 'members' axis, a member axis sharding a grid
  axis, and a non-tiling B all decline loudly.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    EnsembleSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.models.state import EnsembleState
from multigpu_advectiondiffusion_tpu.parallel.mesh import (
    Decomposition,
    make_mesh,
)
from multigpu_advectiondiffusion_tpu.resilience.errors import (
    EnsembleMemberDivergedError,
)


def _diff_cfg(impl="xla", shape=(16, 12, 10)):
    g = Grid.make(*reversed(shape), lengths=tuple(
        0.1 * n for n in reversed(shape)
    ))
    return DiffusionConfig(grid=g, diffusivity=1.0, dtype="float32",
                           impl=impl, ic="gaussian")


def _members(B):
    return [
        {"ic_params": (("width", 0.1 + 0.02 * i),)} for i in range(B)
    ]


def _run_pair(solver_cls, cfg, members, mesh, decomp=None, iters=3):
    """Batched run under the mesh vs the PR 9 single-device ensemble
    (same member set)."""
    es_ref = EnsembleSolver(solver_cls, cfg, members)
    out_ref = es_ref.run(es_ref.initial_state(), iters)
    es_mesh = EnsembleSolver(solver_cls, cfg, members, mesh=mesh,
                             decomp=decomp)
    out_mesh = es_mesh.run(es_mesh.initial_state(), iters)
    return es_mesh, out_mesh, out_ref


# --------------------------------------------------------------------- #
# Bit-exactness: members-only and members x z-slab vs PR 9 single-device
# --------------------------------------------------------------------- #
def test_members_only_mesh_b8_bit_exact_diffusion(devices):
    mesh = make_mesh({"members": 8})
    es, out, ref = _run_pair(DiffusionSolver, _diff_cfg(), _members(8),
                             mesh)
    eng = es.engaged_path()
    assert eng["stepper"] == "ensemble-vmap[generic-xla]"
    assert eng["devices"] == 8 and eng["member_sharding"] == 8
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(out.t), np.asarray(ref.t))


def test_members_x_zslab_mesh_b8_bit_exact_diffusion(devices):
    mesh = make_mesh({"members": 4, "dz": 2})
    es, out, ref = _run_pair(
        DiffusionSolver, _diff_cfg(), _members(8), mesh,
        decomp=Decomposition.slab("dz"),
    )
    eng = es.engaged_path()
    assert eng["member_sharding"] == 4 and eng["devices"] == 8
    assert eng["mesh"] == "members:4,dz:2"
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))


def test_members_mesh_b8_ulp_weno5_burgers(devices):
    cfg = BurgersConfig(grid=Grid.make(24, 8, 8, lengths=2.0), nu=1e-5,
                        adaptive_dt=False, dtype="float32", impl="xla")
    mesh = make_mesh({"members": 8})
    es, out, ref = _run_pair(BurgersSolver, cfg, _members(8), mesh,
                             iters=2)
    # WENO under a resharded lowering reassociates at ulp level — the
    # PR 4/PR 9 equality grade (diffusion bit-exact, WENO ulp)
    np.testing.assert_allclose(
        np.asarray(out.u), np.asarray(ref.u), rtol=0, atol=1e-6,
    )


def test_members_x_zslab_ulp_weno5_burgers(devices):
    cfg = BurgersConfig(grid=Grid.make(24, 8, 16, lengths=2.0), nu=1e-5,
                        adaptive_dt=False, dtype="float32", impl="xla")
    mesh = make_mesh({"members": 4, "dz": 2})
    es, out, ref = _run_pair(BurgersSolver, cfg, _members(8), mesh,
                             decomp=Decomposition.slab("dz"), iters=2)
    np.testing.assert_allclose(
        np.asarray(out.u), np.asarray(ref.u), rtol=0, atol=1e-6,
    )


def test_member_varying_operands_under_members_mesh(devices):
    """Scalar sweeps (generic rung, batched operands) compose with
    member sharding: per-member K and per-member step counts survive
    the resharding bit-exact."""
    mesh = make_mesh({"members": 4})
    members = [{"diffusivity": k} for k in (0.5, 1.0, 1.5, 2.0)]
    cfg = _diff_cfg()
    es_ref = EnsembleSolver(DiffusionSolver, cfg, members)
    est = es_ref.initial_state()
    t_end = float(est.t[0]) + 0.002
    ref = es_ref.advance_to(est, t_end)
    es = EnsembleSolver(DiffusionSolver, cfg, members, mesh=mesh)
    out = es.advance_to(es.initial_state(), t_end)
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))
    np.testing.assert_array_equal(np.asarray(out.it), np.asarray(ref.it))


# --------------------------------------------------------------------- #
# The B-folded slab rung
# --------------------------------------------------------------------- #
def test_b_folded_slab_bit_exact_vs_per_member_slab_runs():
    cfg = _diff_cfg("pallas_slab")
    es = EnsembleSolver(DiffusionSolver, cfg, _members(4))
    out = es.run(es.initial_state(), 2)
    assert es.engaged_path()["stepper"] == (
        "ensemble-fold[fused-whole-run-slab]"
    )
    for i in range(4):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 2)
        assert ms.engaged_path()["stepper"] == "fused-whole-run-slab"
        np.testing.assert_array_equal(
            np.asarray(out.u[i]), np.asarray(ref.u),
            err_msg=f"member {i} diverged from its slab single run",
        )


def test_b_folded_slab_under_members_mesh_bit_exact(devices):
    cfg = _diff_cfg("pallas_slab")
    mesh = make_mesh({"members": 4})
    es_ref = EnsembleSolver(DiffusionSolver, cfg, _members(8))
    ref = es_ref.run(es_ref.initial_state(), 2)
    es = EnsembleSolver(DiffusionSolver, cfg, _members(8), mesh=mesh)
    out = es.run(es.initial_state(), 2)
    assert es.engaged_path()["stepper"] == (
        "ensemble-fold[fused-whole-run-slab]"
    )
    assert es.engaged_path()["member_sharding"] == 4
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))


def test_slab_pin_over_spatial_subgroup_declines_loudly(devices):
    mesh = make_mesh({"members": 4, "dz": 2})
    with pytest.raises(ValueError, match="spatial"):
        EnsembleSolver(
            DiffusionSolver, _diff_cfg("pallas_slab"), _members(8),
            mesh=mesh, decomp=Decomposition.slab("dz"),
        )


# --------------------------------------------------------------------- #
# Member-attributed divergence under sharding
# --------------------------------------------------------------------- #
def test_diverging_member_named_under_sharding(devices):
    mesh = make_mesh({"members": 4})
    es = EnsembleSolver(DiffusionSolver, _diff_cfg(), _members(8),
                        mesh=mesh)
    est = es.initial_state()
    bad = est.u.at[5, 4, 5, 6].set(jnp.nan)
    est = EnsembleState(u=bad, t=est.t, it=est.it)
    out = es.run(est, 2)
    with pytest.raises(EnsembleMemberDivergedError) as exc:
        es.check_health(out)
    assert exc.value.members == [5]
    # every healthy member stays bit-exact vs its looped single run
    for i in (0, 3, 7):
        ms = es.member_solver(i)
        ref = ms.run(ms.initial_state(), 2)
        np.testing.assert_array_equal(
            np.asarray(out.u[i]), np.asarray(ref.u),
            err_msg=f"healthy member {i} was poisoned under sharding",
        )


# --------------------------------------------------------------------- #
# Measured batched tuning
# --------------------------------------------------------------------- #
def test_tuner_measures_batched_candidates_at_actual_b(
        devices, tmp_path):
    from multigpu_advectiondiffusion_tpu import tuning

    tuning.configure(cache_path=str(tmp_path / "tuning.json"),
                     enabled=True)
    try:
        cfg = dataclasses.replace(_diff_cfg(shape=(12, 10, 8)),
                                  impl="auto")
        mesh = make_mesh({"members": 8})
        mpath = str(tmp_path / "ev.jsonl")
        with telemetry.capture(mpath):
            es = EnsembleSolver(DiffusionSolver, cfg, 16, mesh=mesh)
        assert es._tuned["source"] == "measured"
        assert es._tuned["ensemble"] == 16
        assert es._tuned["member_sharding"] == 8
        evs = [json.loads(line) for line in open(mpath)]
        meas = [e for e in evs if e["kind"] == "tune"
                and e["name"] == "measure"]
        # the measurement happened AT the batched shape: every row
        # carries B (no single-run proxy)
        assert meas and all(e.get("ensemble") == 16 for e in meas)
        impls = {e["impl"] for e in meas if "mlups" in e}
        assert "xla" in impls  # generic rung always races
        # warm construction resolves from the cache without re-measuring
        es2 = EnsembleSolver(DiffusionSolver, cfg, 16, mesh=mesh)
        assert es2._tuned["source"] == "cache"
        # a different mesh layout is a different key
        es3 = EnsembleSolver(DiffusionSolver, cfg, 16)
        assert es3._tuned["key"] != es._tuned["key"]
    finally:
        tuning.configure(enabled=False,
                         cache_path=os.environ.get(
                             "TPUCFD_TUNING_CACHE", ""))


# --------------------------------------------------------------------- #
# Loud declines
# --------------------------------------------------------------------- #
def test_spatial_only_mesh_needs_members_axis(devices):
    mesh = make_mesh({"dz": 2}, devices=devices[:2])
    with pytest.raises(ValueError, match="members"):
        EnsembleSolver(DiffusionSolver, _diff_cfg(), 4, mesh=mesh,
                       decomp=Decomposition.slab("dz"))


def test_member_axis_may_not_shard_a_grid_axis(devices):
    mesh = make_mesh({"members": 2})
    with pytest.raises(ValueError, match="halo-free"):
        EnsembleSolver(DiffusionSolver, _diff_cfg(), 4, mesh=mesh,
                       decomp=Decomposition.slab("members"))


def test_non_tiling_member_count_declines(devices):
    mesh = make_mesh({"members": 8})
    with pytest.raises(ValueError, match="multiple"):
        EnsembleSolver(DiffusionSolver, _diff_cfg(), 6, mesh=mesh)
