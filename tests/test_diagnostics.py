"""In-situ physics diagnostics suite (ISSUE 8 tentpole, tier-1, CPU).

Covers the diagnostics layer end to end: the fused observable suite's
values against hand-computed references, the ONE-jitted-probe
compile-count proof (the suite adds zero compiled programs beyond the
sentinel's probe), the tolerance rules and their strict escalation
through the rollback path, downsampled rotation-capped snapshot
streaming, the science gate's trajectory comparator, and a real
supervised CLI run whose ``--metrics`` stream carries ``phys:diag``
events, whose ``summary.json`` gains the diagnostics block, and whose
``tpucfd-trace`` report renders the physics section.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from multigpu_advectiondiffusion_tpu import (
    BurgersConfig,
    BurgersSolver,
    DiffusionConfig,
    DiffusionSolver,
    Grid,
    telemetry,
)
from multigpu_advectiondiffusion_tpu.cli.__main__ import main as cli_main
from multigpu_advectiondiffusion_tpu.diagnostics import (
    compare as science,
    physics,
)
from multigpu_advectiondiffusion_tpu.resilience.errors import (
    PhysicsViolationError,
)
from multigpu_advectiondiffusion_tpu.resilience.sentinel import (
    DivergenceSentinel,
    make_health_probe,
)
from multigpu_advectiondiffusion_tpu.resilience.supervisor import (
    supervise_run,
)
from multigpu_advectiondiffusion_tpu.telemetry import schema
from multigpu_advectiondiffusion_tpu.utils import io as io_utils


def _events(path) -> list:
    with open(path) as f:
        return [json.loads(line) for line in f]


def _diffusion3d(**kw):
    kw.setdefault("grid", Grid.make(12, 10, 8, lengths=10.0))
    return DiffusionSolver(DiffusionConfig(dtype="float32", **kw))


# --------------------------------------------------------------------- #
# Fused observables: values against hand-computed references
# --------------------------------------------------------------------- #
def test_probe_observables_match_numpy():
    solver = _diffusion3d()
    state = solver.initial_state()
    probe = make_health_probe(solver, diagnostics=True)
    stats = probe(state)
    u = np.asarray(state.u, np.float64)
    vol = float(np.prod(solver.grid.spacing))
    assert stats["l1"] == pytest.approx(vol * np.abs(u).sum(), rel=1e-5)
    assert stats["energy"] == pytest.approx(vol * (u * u).sum(), rel=1e-5)
    tv = sum(np.abs(np.diff(u, axis=a)).sum() for a in range(u.ndim))
    assert stats["tv"] == pytest.approx(tv, rel=1e-5)
    spec = np.abs(np.fft.rfft(u, axis=-1)) ** 2
    cut = max(1, (2 * spec.shape[-1]) // 3)
    assert stats["spectral_tail"] == pytest.approx(
        spec[..., cut:].sum() / spec.sum(), rel=1e-4
    )
    # the base probe scalars are unchanged by fusing the suite in
    base = make_health_probe(solver, diagnostics=False)(state)
    for key in ("max_abs", "min", "max", "l2", "mass"):
        assert stats[key] == pytest.approx(base[key], rel=1e-6)


def test_probe_observables_sharded_global(devices):
    """The fused suite's sums reduce across the mesh: a 2-device z-slab
    run reports the same global budgets as the unsharded probe (TV is
    shard-local by construction — its one missing interface plane is
    bounded by the field's values there and stays inside the
    monotonicity tolerance; the budgets must be exact)."""
    from multigpu_advectiondiffusion_tpu.parallel.mesh import (
        Decomposition,
        make_mesh,
    )

    grid = Grid.make(12, 10, 8, lengths=10.0)
    ref = DiffusionSolver(DiffusionConfig(grid=grid, dtype="float32"))
    ref_stats = make_health_probe(ref, diagnostics=True)(
        ref.initial_state()
    )
    sharded = DiffusionSolver(
        DiffusionConfig(grid=grid, dtype="float32"),
        mesh=make_mesh({"dz": 2}, devices=devices[:2]),
        decomp=Decomposition.slab("dz"),
    )
    stats = make_health_probe(sharded, diagnostics=True)(
        sharded.initial_state()
    )
    for key in ("l1", "energy", "mass", "l2", "max", "min",
                "spectral_tail"):
        assert stats[key] == pytest.approx(ref_stats[key], rel=1e-5), key
    # shard-local TV misses exactly the inter-shard interface planes
    assert stats["tv"] == pytest.approx(ref_stats["tv"], rel=0.05)
    assert stats["tv"] <= ref_stats["tv"] + 1e-6


# --------------------------------------------------------------------- #
# Compile-count proof: the suite adds NO second compiled probe program
# --------------------------------------------------------------------- #
def test_diagnostics_add_no_second_compiled_probe():
    """The whole diagnostic suite rides the sentinel's ONE jitted probe:
    constructing a diagnostics-armed sentinel calls the solver's _wrap
    (= jax.jit) exactly once, and repeated probes never retrace — the
    one-compile-per-program discipline of tests/test_xprof.py applied
    to the probe."""
    solver = _diffusion3d()
    wraps = []
    orig = solver._wrap

    def counting_wrap(*a, **kw):
        wraps.append(a)
        return orig(*a, **kw)

    solver._wrap = counting_wrap
    sentinel = DivergenceSentinel(solver, diagnostics=True)
    assert len(wraps) == 1, "the diagnostic suite built a second program"
    state = solver.initial_state()
    sentinel.arm(state)
    for _ in range(3):
        state = solver.run(state, 2)
        sentinel.check(state)
    # the block traced once: 4 probes, 1 compilation, full suite present
    assert sentinel._probe.traces["count"] == 1
    assert "tv" in (sentinel.stats or {})
    assert "spectral_tail" in sentinel.stats


def test_probe_keys_registered_and_events_validate(tmp_path):
    """phys:diag / phys:violation / io:snapshot_write events pass the
    schema registry's structural validation."""
    ev = {"t": 0.0, "proc": 0, "kind": "phys", "name": "diag",
          "step": 1, "time": 0.1, "solver": "DiffusionSolver"}
    assert schema.validate_event(ev) == []
    ev = {"t": 0.0, "proc": 0, "kind": "phys", "name": "violation",
          "step": 1, "time": 0.1, "rule": "tv_monotone", "message": "x",
          "tolerance": 0.05}
    assert schema.validate_event(ev) == []
    ev = {"t": 0.0, "proc": 0, "kind": "io", "name": "snapshot_write",
          "path": "p", "bytes": 1, "seconds": 0.0, "iteration": 4,
          "stride": 2}
    assert schema.validate_event(ev) == []
    assert schema.validate_event(
        {"t": 0, "proc": 0, "kind": "phys", "name": "diag"}
    )  # missing required fields flagged


# --------------------------------------------------------------------- #
# Violation rules
# --------------------------------------------------------------------- #
def test_max_principle_rule_trips_on_new_extremum():
    rule = physics.max_principle_rule(tolerance=1e-3)
    base = {"max": 1.0, "min": 0.0}
    assert rule.check({"max": 1.0, "min": 0.0}, base, rule.tolerance) is None
    assert rule.check({"max": 1.0005, "min": 0.0}, base,
                      rule.tolerance) is None  # inside the band
    assert "maximum principle" in rule.check(
        {"max": 1.01, "min": 0.0}, base, rule.tolerance
    )
    assert "undercuts" in rule.check(
        {"max": 1.0, "min": -0.01}, base, rule.tolerance
    )


def test_tv_monotone_rule_trips_on_growth():
    rule = physics.tv_monotone_rule(tolerance=0.05)
    base = {"tv": 10.0}
    assert rule.check({"tv": 9.0}, base, rule.tolerance) is None
    assert rule.check({"tv": 10.4}, base, rule.tolerance) is None
    assert "total variation" in rule.check(
        {"tv": 11.0}, base, rule.tolerance
    )


def test_supervised_clean_run_emits_diag_no_violation(tmp_path):
    solver = _diffusion3d()
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        out, report = supervise_run(
            solver, solver.initial_state(), iters=8,
            sentinel_every=2, diag_every=2,
        )
    assert int(out.it) == 8
    diag = report.diagnostics
    assert diag is not None
    assert diag["rules"] == ["max_principle"]
    assert len(diag["trajectory"]) == 2  # probes 2,4,6,8 -> diag 4,8
    assert diag["violations"] == []
    assert "tv" in diag["baseline"]
    evs = _events(path)
    diags = [e for e in evs if (e["kind"], e["name"]) == ("phys", "diag")]
    assert len(diags) == 2
    assert diags[-1]["solver"] == "DiffusionSolver"
    assert diags[-1]["decay_rate_analytic"] == -1.5
    for e in diags:
        assert schema.validate_event(e) == []
    assert not [e for e in evs if e["kind"] == "phys"
                and e["name"] == "violation"]


def test_strict_violation_escalates_into_rollback(tmp_path):
    """A tolerance breach under --diag-strict recovers through the SAME
    rollback + dt-backoff path as a divergence (an always-firing
    injected rule exhausts the budget and propagates), with the
    violation and rollback both in the event stream; without strict it
    is a warning event only (next test)."""
    solver = _diffusion3d()
    # an always-firing rule: deterministic injection without faking
    # the field
    rule = physics.ViolationRule(
        "always", 0.0, lambda stats, base, tol: "injected breach"
    )
    orig_spec = solver.diagnostics_spec
    solver.diagnostics_spec = lambda: {**orig_spec(), "rules": [rule]}
    dt0 = solver.dt
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        with pytest.raises(PhysicsViolationError) as err:
            supervise_run(
                solver, solver.initial_state(), iters=6,
                sentinel_every=2, diag_every=1, diag_strict=True,
                max_retries=2,
            )
    assert "injected breach" in str(err.value)
    assert solver.dt < dt0  # the dt backoff engaged before exhausting
    evs = _events(path)
    kinds = [(e["kind"], e["name"]) for e in evs]
    assert ("phys", "violation") in kinds
    assert ("resilience", "rollback") in kinds
    assert ("resilience", "retries_exhausted") in kinds


def test_non_strict_violation_is_warning_only(tmp_path):
    solver = _diffusion3d()
    rule = physics.ViolationRule(
        "always", 0.0, lambda stats, base, tol: "injected breach"
    )
    orig_spec = solver.diagnostics_spec
    solver.diagnostics_spec = lambda: {**orig_spec(), "rules": [rule]}
    path = str(tmp_path / "ev.jsonl")
    with telemetry.capture(path):
        out, report = supervise_run(
            solver, solver.initial_state(), iters=6,
            sentinel_every=2, diag_every=1,
        )
    assert int(out.it) == 6 and report.retries == 0
    assert len(report.diagnostics["violations"]) == 3
    viols = [e for e in _events(path)
             if (e["kind"], e["name"]) == ("phys", "violation")]
    assert len(viols) == 3
    for e in viols:
        assert schema.validate_event(e) == []


def test_diag_requires_sentinel_cadence():
    solver = _diffusion3d()
    with pytest.raises(ValueError, match="sentinel_every"):
        supervise_run(solver, solver.initial_state(), iters=4,
                      diag_every=1)


# --------------------------------------------------------------------- #
# Gaussian decay-rate fit
# --------------------------------------------------------------------- #
def test_gaussian_decay_fit_exact_power_law():
    times = [0.1 * 1.3 ** i for i in range(6)]
    maxima = [t ** -1.5 for t in times]
    fit = physics.gaussian_decay_fit(times, maxima, analytic_rate=-1.5)
    assert fit["measured_rate"] == pytest.approx(-1.5, abs=1e-9)
    assert fit["rel_err"] < 1e-9
    assert physics.gaussian_decay_fit([0.1], [1.0]) is None


# --------------------------------------------------------------------- #
# Snapshot streaming
# --------------------------------------------------------------------- #
def test_snapshot_streamer_atomic_downsampled_capped(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    u = np.arange(16 * 12, dtype=np.float32).reshape(16, 12)
    with telemetry.capture(path):
        with io_utils.SnapshotStreamer(
            str(tmp_path / "snaps"), stride=2,
            max_bytes=3 * (8 * 6 * 4),  # exactly three snapshots
        ) as streamer:
            for it in range(2, 12, 2):
                streamer.write(u + it, it)
    snaps = sorted(os.listdir(tmp_path / "snaps"))
    # rotation kept the newest 3; no torn .tmp files left behind
    assert snaps == ["snap_000006.bin", "snap_000008.bin",
                     "snap_000010.bin"]
    got = np.fromfile(tmp_path / "snaps" / "snap_000010.bin",
                      dtype=np.float32)
    np.testing.assert_array_equal(got, (u + 10)[::2, ::2].ravel())
    evs = [e for e in _events(path)
           if (e["kind"], e["name"]) == ("io", "snapshot_write")]
    assert len(evs) == 5  # every write published exactly once
    assert all(e["stride"] == 2 and e["bytes"] == 8 * 6 * 4 for e in evs)
    assert [e["iteration"] for e in evs] == [2, 4, 6, 8, 10]


def test_snapshot_streamer_keeps_newest_even_over_cap(tmp_path):
    with io_utils.SnapshotStreamer(str(tmp_path), max_bytes=4) as s:
        s.write(np.ones(64, np.float32), 1)
        s.write(np.ones(64, np.float32), 2)
    assert sorted(os.listdir(tmp_path)) == ["snap_000002.bin"]


def test_cli_snapshots_need_sentinel(tmp_path):
    with pytest.raises(ValueError, match="sentinel-every"):
        cli_main([
            "diffusion2d", "--n", "12", "10", "--iters", "4",
            "--snapshots", "2", "--save", str(tmp_path / "run"),
        ])
    with pytest.raises(ValueError, match="sentinel-every"):
        cli_main([
            "diffusion2d", "--n", "12", "10", "--iters", "4",
            "--diag-every", "1", "--save", str(tmp_path / "run"),
        ])
    with pytest.raises(ValueError, match="diag-every"):
        cli_main([
            "diffusion2d", "--n", "12", "10", "--iters", "4",
            "--sentinel-every", "2", "--diag-strict",
            "--save", str(tmp_path / "run"),
        ])


# --------------------------------------------------------------------- #
# Heavy variants (slow-marked: tier-1 stays inside the 870 s window)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_spectral_tail_detects_steepening_slow():
    """The under-resolution detector end to end: a smooth sine under
    inviscid Burgers steepens into a shock — the spectral tail ratio
    must grow by orders of magnitude as energy piles into the grid
    cutoff, well before the divergence sentinel would see anything."""
    grid = Grid.make_periodic(512, lengths=2.0, origin=-1.0)
    solver = BurgersSolver(
        BurgersConfig(grid=grid, flux="burgers", ic="sine",
                      bc="periodic", dtype="float64")
    )
    state = solver.initial_state()
    probe = make_health_probe(solver, diagnostics=True)
    tail0 = probe(state)["spectral_tail"]
    out = solver.advance_to(state, 0.4)  # shock forms at t = 1/pi
    tail1 = probe(out)["spectral_tail"]
    assert tail1 > max(tail0 * 100, 1e-9), (tail0, tail1)


@pytest.mark.slow
def test_snapshot_stream_long_run_stays_capped_slow(tmp_path):
    """A long supervised run streaming many snapshots stays inside the
    byte cap: the directory never holds more than cap + one snapshot."""
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    nbytes = 64 * 48 * 4  # full-resolution f32 snapshot
    cli_main([
        "diffusion2d", "--n", "64", "48", "--iters", "60",
        "--sentinel-every", "2", "--snapshots", "2",
        "--snapshot-max-bytes", str(3 * nbytes),
        "--save", str(run), "--metrics", mpath,
    ])
    snaps = [p for p in os.listdir(run) if p.startswith("snap_")]
    assert len(snaps) == 3  # 30 written, rotation kept the newest 3
    assert max(snaps) == "snap_000060.bin"
    writes = [e for e in _events(mpath)
              if (e["kind"], e["name"]) == ("io", "snapshot_write")]
    assert len(writes) == 30


# --------------------------------------------------------------------- #
# Science gate comparator
# --------------------------------------------------------------------- #
def _round(**runs) -> dict:
    return {"schema": 1, "runs": {
        name: {"meta": {}, "observables": obs}
        for name, obs in runs.items()
    }}


def test_science_compare_identical_passes():
    traj = {"mass": [[5, 1.0], [10, 0.9]], "tv": [[5, 3.0], [10, 2.5]]}
    result = science.compare(_round(d=traj), _round(d=traj))
    assert result.ok
    assert {r.status for r in result.rows} == {"ok"}


def test_science_compare_trips_on_drift_and_coverage():
    old = _round(d={"mass": [[5, 1.0], [10, 0.9]],
                    "tv": [[5, 3.0], [10, 2.5]]})
    drifted = _round(d={"mass": [[5, 1.0], [10, 0.89]],
                        "tv": [[5, 3.0], [10, 2.5]]})
    result = science.compare(drifted, old)
    assert not result.ok
    assert [r.observable for r in result.regressions] == ["mass"]
    # a silently dropped observable is a coverage regression
    missing = _round(d={"mass": [[5, 1.0], [10, 0.9]]})
    result = science.compare(missing, old)
    assert [r.observable for r in result.regressions] == ["tv"]
    # a dropped run fails; an added run never does
    result = science.compare(_round(), old)
    assert not result.ok and result.rows[0].status == "missing"
    result = science.compare(old, _round())
    assert result.ok


def test_science_compare_band_overrides():
    old = _round(d={"tv": [[5, 10.0]]})
    new = _round(d={"tv": [[5, 10.2]]})
    assert not science.compare(new, old).ok  # 2% > 1e-3 band
    assert science.compare(new, old, bands={"tv": 0.05}).ok


def test_science_extract_roundtrip(tmp_path):
    solver = _diffusion3d()
    out, report = supervise_run(
        solver, solver.initial_state(), iters=6,
        sentinel_every=2, diag_every=1,
    )
    summary = {"name": "d3", "resilience": report.to_dict()}
    # the CLI surfaces diagnostics top-level; both layouts must extract
    p1 = tmp_path / "s1.json"
    p1.write_text(json.dumps(summary))
    artifact = science.extract([str(p1)])
    obs = artifact["runs"]["d3"]["observables"]
    assert "mass" in obs and "tv" in obs and "time" in obs
    assert len(obs["mass"]) == 3
    assert science.compare(artifact, artifact).ok


# --------------------------------------------------------------------- #
# CLI acceptance: events + summary block + trace-report section
# --------------------------------------------------------------------- #
def test_cli_supervised_diag_snapshot_stream(tmp_path):
    run = tmp_path / "run"
    mpath = str(tmp_path / "events.jsonl")
    cli_main([
        "diffusion3d", "--n", "12", "10", "8", "--iters", "8",
        "--sentinel-every", "2", "--diag-every", "2",
        "--snapshots", "4", "--snapshot-stride", "2",
        "--snapshot-max-bytes", "4096",
        "--save", str(run), "--metrics", mpath,
    ])
    evs = _events(mpath)
    diags = [e for e in evs if (e["kind"], e["name"]) == ("phys", "diag")]
    assert len(diags) == 2 and diags[-1]["tv"] > 0
    snaps = [e for e in evs
             if (e["kind"], e["name"]) == ("io", "snapshot_write")]
    assert [e["iteration"] for e in snaps] == [4, 8]
    assert (run / "snap_000004.bin").exists()
    # stride 2 on (8, 10, 12) -> (4, 5, 6) f32
    assert (run / "snap_000008.bin").stat().st_size == 4 * 5 * 6 * 4
    summary = json.loads((run / "summary.json").read_text())
    assert summary["schema"] >= 4
    diag = summary["diagnostics"]
    assert len(diag["trajectory"]) == 2
    assert diag["rules"] == ["max_principle"]
    assert diag["violations"] == []
    assert "spectral_tail" in diag["trajectory"][-1]
    # the extractor consumes the CLI summary directly
    artifact = science.extract([str(run / "summary.json")])
    assert "diffusion3d" in artifact["runs"]
    # ... and the trace report renders the physics section with the fit
    from multigpu_advectiondiffusion_tpu.telemetry.analyze import analyze

    report = analyze([mpath])
    assert report.physics["trajectories"], "no physics section"
    tr = report.physics["trajectories"][0]
    assert tr["solver"] == "DiffusionSolver"
    assert "tv" in tr["observables"]
    text = report.format_text()
    assert "physics diagnostics" in text
    assert "no tolerance-rule violations" in text
