"""The examples/ scripts are the reference's run.sh harness — they must
actually run, not just read well. Each is exercised end-to-end on the
virtual mesh with the documented shrink-override pattern
(``examples/README.md``: trailing arguments override the script's).

Subprocess-per-script: the scripts pin their own mesh/platform via the
environment, which must not leak into this process's backend.
"""

import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def _run_script(name, extra, timeout=600):
    res = subprocess.run(
        ["sh", os.path.join(REPO, "examples", name)] + extra,
        capture_output=True,
        text=True,
        cwd=REPO,
        env=_ENV,
        timeout=timeout,
    )
    assert res.returncode == 0, (
        f"{name} failed:\n{res.stdout[-2000:]}\n{res.stderr[-2000:]}"
    )
    return res.stdout


@pytest.mark.parametrize(
    "script,extra,result_shape",
    [
        # 3-D multigpu: tuned fused kernels + split overlap under dz=2
        ("multigpu_diffusion3d.sh",
         ["--n", "32", "16", "16", "--iters", "4",
          "--save", "out/_ex_d3"], (16, 16, 32)),
        # 2-D multigpu: the per-stage whole-shard fused kernels under dy=2
        ("multigpu_burgers2d.sh",
         ["--n", "32", "32", "--t-end", "0.05",
          "--save", "out/_ex_b2"], (32, 32)),
        # single-GPU ladder script (whole-run VMEM stepper)
        ("singlegpu_diffusion2d.sh",
         ["--n", "48", "48", "--iters", "5",
          "--save", "out/_ex_s2"], (48, 48)),
        # the MATLAB WENO7 driver analog (halo-4 fused stepper,
        # adaptive dt)
        ("matlab_weno7_3d.sh",
         ["--n", "24", "16", "16", "--t-end", "0.05",
          "--save", "out/_ex_w7"], (16, 16, 24)),
    ],
)
def test_example_script_runs(tmp_path, script, extra, result_shape):
    from multigpu_advectiondiffusion_tpu.utils.io import load_binary

    save = str(tmp_path / "out")
    # replace the script's save dir with a per-test one (trailing args
    # override, exactly as examples/README.md prescribes)
    extra = [a if not a.startswith("out/_ex") else save for a in extra]
    out = _run_script(script, extra)
    assert "kernel path" in out  # the engaged-path PrintSummary line
    u = load_binary(os.path.join(save, "result.bin"), result_shape)
    assert np.isfinite(u).all()


def test_multihost_example_script_runs(tmp_path):
    """The mpirun-analog launcher: two cooperating CLI processes on the
    virtual backend, exactly the demo line examples/README.md documents
    (4 virtual devices per process, dz_dcn=2 x dz_ici=4 needs 8 global)."""
    import socket

    from multigpu_advectiondiffusion_tpu.utils.io import load_binary

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    save = str(tmp_path / "out")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PORT": str(port),
    }
    res = subprocess.run(
        ["sh", os.path.join(REPO, "examples", "multihost_diffusion3d.sh"),
         "--impl", "xla", "--overlap", "padded",
         "--n", "16", "16", "24", "--iters", "3",
         "--checkpoint-every", "0", "--save", save],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=600,
    )
    assert res.returncode == 0, (
        f"multihost script failed:\n{res.stdout[-2000:]}\n"
        f"{res.stderr[-2000:]}"
    )
    u = load_binary(os.path.join(save, "result.bin"), (24, 16, 16))
    assert np.isfinite(u).all()
