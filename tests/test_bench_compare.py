"""Bench regression gate (ISSUE 6): bench/compare.py + out/bench_gate.sh.

Acceptance: the gate flags an injected 20% throughput regression
against the real archived r05 round while passing the unmodified round.
"""

from __future__ import annotations

import glob
import json
import os

import pytest

from multigpu_advectiondiffusion_tpu.bench import compare as cmp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _newest_round():
    rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    if not rounds:
        pytest.skip("no archived BENCH_r0*.json rounds in this checkout")
    return rounds[-1]


# --------------------------------------------------------------------- #
# Loading: every artifact container the trajectory uses
# --------------------------------------------------------------------- #
def test_load_rows_jsonl(tmp_path):
    p = tmp_path / "rows.json"
    p.write_text(
        '{"metric": "a_mlups", "value": 100.0, "spread": 0.01}\n'
        '{"metric": "b_mlups", "value": 50.0}\n'
        "not json at all\n"
    )
    rows = cmp.load_rows(str(p))
    assert set(rows) == {"a_mlups", "b_mlups"}
    assert cmp.row_value(rows["a_mlups"]) == 100.0
    assert cmp.row_spread(rows["a_mlups"]) == 0.01


def test_load_rows_driver_wrapper_with_torn_head(tmp_path):
    tail = (
        'alue": 1.0}\n'  # torn first line, as in the archived rounds
        '{"metric": "a_mlups", "value": 100.0, "spread": 0.002}\n'
    )
    p = tmp_path / "wrap.json"
    p.write_text(json.dumps({"n": 5, "cmd": "bench", "rc": 0,
                             "tail": tail}))
    rows = cmp.load_rows(str(p))
    assert set(rows) == {"a_mlups"}


def test_load_rows_matrix_name_mlups(tmp_path):
    p = tmp_path / "matrix.json"
    p.write_text('{"name": "diffusion3d", "mlups": 42000.5}\n')
    rows = cmp.load_rows(str(p))
    assert cmp.row_value(rows["diffusion3d"]) == 42000.5


def test_load_rows_real_archived_round():
    rows = cmp.load_rows(_newest_round())
    assert rows, "the archived round parsed to zero rows"
    assert all(cmp.row_value(r) is not None for r in rows.values())


# --------------------------------------------------------------------- #
# Comparison semantics
# --------------------------------------------------------------------- #
def _rows(**vals):
    return {
        k: {"metric": k, "value": v[0], "spread": v[1]}
        for k, v in vals.items()
    }


def test_compare_flags_regression_beyond_threshold():
    old = _rows(a=(100.0, 0.01))
    new = _rows(a=(79.0, 0.01))  # -21%
    res = cmp.compare(new, old)
    assert not res.ok
    assert res.rows[0].status == "regression"


def test_compare_noise_threshold_scales_with_spread():
    old = _rows(a=(100.0, 0.15))  # noisy row: 2x0.15 = 30% threshold
    res = cmp.compare(_rows(a=(88.0, 0.01)), old)
    assert res.ok, "a -12% move on a 15%-spread row is noise, not signal"
    res = cmp.compare(_rows(a=(60.0, 0.01)), old)
    assert not res.ok


def test_compare_improvement_and_ok():
    old = _rows(a=(100.0, 0.0), b=(100.0, 0.0))
    res = cmp.compare(_rows(a=(120.0, 0.0), b=(101.0, 0.0)), old)
    assert res.ok
    statuses = {r.metric: r.status for r in res.rows}
    assert statuses == {"a": "improved", "b": "ok"}


def test_compare_missing_row_is_coverage_regression():
    old = _rows(a=(100.0, 0.0), b=(50.0, 0.0))
    res = cmp.compare(_rows(a=(100.0, 0.0)), old)
    assert not res.ok
    assert any(r.status == "missing" and r.metric == "b"
               for r in res.rows)
    # a NEW metric never fails the gate
    res = cmp.compare(_rows(a=(100.0, 0.0), c=(1.0, 0.0)),
                      _rows(a=(100.0, 0.0)))
    assert res.ok
    assert any(r.status == "added" for r in res.rows)


def test_check_floors():
    rows = {
        "a": {"metric": "a", "value": 10.0, "vs_baseline": 1.2},
        "b": {"metric": "b", "value": 10.0, "vs_baseline": 0.9},
        "c": {"metric": "c", "value": 10.0},  # no baseline: skipped
    }
    res = cmp.check_floors(rows)
    assert not res.ok
    statuses = {r.metric: r.status for r in res.rows}
    assert statuses == {"a": "ok", "b": "regression"}


# --------------------------------------------------------------------- #
# Acceptance: the r05 gate
# --------------------------------------------------------------------- #
def test_gate_passes_unmodified_r05_round():
    rows = cmp.load_rows(_newest_round())
    assert cmp.compare(rows, rows).ok


def test_gate_trips_on_injected_20pct_regression():
    # This acceptance case depends on DEFAULT_SPREAD_CAP: without the
    # cap, an archived round whose victim row carries a large measured
    # spread (a CPU-round artifact) can widen its own threshold past
    # 20% and swallow the injected regression — the seed's original
    # failure mode. The cap (0.15) bounds the spread-derived slack
    # below the injection, so this must trip for EVERY archived round.
    rows = cmp.load_rows(_newest_round())
    slowed = {k: dict(v) for k, v in rows.items()}
    victim = sorted(slowed)[0]
    slowed[victim]["value"] = cmp.row_value(slowed[victim]) * 0.8
    res = cmp.compare(slowed, rows)
    assert not res.ok
    bad = [r for r in res.rows if r.status == "regression"]
    assert [r.metric for r in bad] == [victim]
    assert "REGRESSION" in res.format_text()
    assert "FAIL" in res.format_text()


def test_gate_trips_on_20pct_regression_pinned_fixtures():
    """The injected-regression guarantee, pinned — no dependence on
    whatever BENCH_r0*.json ships in the checkout. A -20% move must
    trip at BOTH spread extremes: a quiet row (threshold = rel_tol)
    and a pathologically noisy row, where DEFAULT_SPREAD_CAP must keep
    the spread-derived slack below the injection."""
    assert cmp.DEFAULT_SPREAD_CAP < 0.20, (
        "spread cap must stay below the 20% acceptance injection"
    )
    for spread in (0.0, 0.02, 0.15, 0.5, 5.0):
        old = {"m_mlups": {"metric": "m_mlups", "value": 100.0,
                           "spread": spread}}
        new = {"m_mlups": {"metric": "m_mlups", "value": 80.0,
                           "spread": spread}}
        res = cmp.compare(new, old)
        assert not res.ok, (
            f"-20% hid inside spread={spread} (threshold "
            f"{res.rows[0].threshold})"
        )
        # and an in-noise move must NOT trip (the cap keeps semantics,
        # it does not turn the gate paranoid)
        ok = {"m_mlups": {"metric": "m_mlups", "value": 97.0,
                          "spread": spread}}
        assert cmp.compare(ok, old).ok, (
            f"-3% tripped at spread={spread}"
        )


# --------------------------------------------------------------------- #
# CLI entry point
# --------------------------------------------------------------------- #
def test_cli_exits_nonzero_on_regression(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text('{"metric": "a", "value": 100.0, "spread": 0.0}\n')
    new.write_text('{"metric": "a", "value": 80.0, "spread": 0.0}\n')
    with pytest.raises(SystemExit) as exc:
        cmp.main([str(new), str(old)])
    assert exc.value.code == 1
    assert "REGRESSION" in capsys.readouterr().out
    # identical rounds pass (returns None, no SystemExit)
    assert cmp.main([str(old), str(old)]) is None
    assert "PASS" in capsys.readouterr().out


def test_cli_floors_mode(tmp_path):
    new = tmp_path / "new.json"
    new.write_text(
        '{"metric": "a", "value": 10.0, "vs_baseline": 1.5}\n'
    )
    assert cmp.main([str(new), "--floors"]) is None
    new.write_text(
        '{"metric": "a", "value": 10.0, "vs_baseline": 0.5}\n'
    )
    with pytest.raises(SystemExit):
        cmp.main([str(new), "--floors"])


def test_cli_requires_exactly_one_mode(tmp_path):
    new = tmp_path / "new.json"
    new.write_text('{"metric": "a", "value": 1.0}\n')
    with pytest.raises(SystemExit):
        cmp.main([str(new)])  # neither prior nor --floors
    with pytest.raises(SystemExit):
        cmp.main([str(new), str(new), "--floors"])  # both


# --------------------------------------------------------------------- #
# Ensemble columns (ISSUE 9): tolerance for pre-ensemble rounds
# --------------------------------------------------------------------- #
def test_old_rounds_without_ensemble_field_read_as_one():
    assert cmp.row_members({"metric": "a", "value": 1.0}) == 1
    assert cmp.row_members({"metric": "a", "ensemble": None}) == 1
    assert cmp.row_members({"metric": "a", "ensemble": "garbage"}) == 1
    assert cmp.row_members({"metric": "a", "ensemble": 64}) == 64


def test_pre_ensemble_baseline_is_not_a_coverage_regression():
    """BENCH_r01-r05 rows carry no `ensemble`/`vs_looped` fields; a new
    round that adds them (plus brand-new ensemble_* metrics) must
    compare clean — no regressions, no notes."""
    old = {"diffusion3d_mlups": {"metric": "diffusion3d_mlups",
                                 "value": 100.0, "spread": 0.01}}
    new = {
        "diffusion3d_mlups": {"metric": "diffusion3d_mlups",
                              "value": 101.0, "spread": 0.01,
                              "ensemble": 1},
        "ensemble_diffusion3d_b64_mlups_members": {
            "metric": "ensemble_diffusion3d_b64_mlups_members",
            "value": 900.0, "spread": 0.02, "ensemble": 64,
            "vs_looped": 3.4,
        },
    }
    res = cmp.compare(new, old)
    assert res.ok, res.format_text()
    assert not res.notes, res.notes
    assert {r.status for r in res.rows} == {"ok", "added"}


def test_dropped_ensemble_columns_note_but_never_gate():
    """The MEASURED_FIELDS discipline for the ensemble columns: a round
    that silently loses them prints a coverage note, exit stays 0."""
    row = {"metric": "ensemble_x_b8_mlups_members", "value": 10.0,
           "ensemble": 8, "vs_looped": 3.0}
    stripped = {"metric": "ensemble_x_b8_mlups_members", "value": 10.0}
    res = cmp.compare({row["metric"]: stripped}, {row["metric"]: row})
    assert res.ok
    assert any("vs_looped" in n for n in res.notes), res.notes
    # member-count DRIFT (a b8 row re-measured at another B) is also a
    # note — the workload changed, the threshold math did not
    res2 = cmp.compare(
        {row["metric"]: {**row, "ensemble": 16}}, {row["metric"]: row}
    )
    assert res2.ok
    assert any("member count changed" in n for n in res2.notes)
