#!/bin/sh
# MultiGPU/Burgers2d_Baseline/run.sh: tEnd=0.4 CFL=0.4, 2x2 domain, 400^2, 2 ranks
python -m multigpu_advectiondiffusion_tpu.cli burgers2d \
    --t-end 0.4 --cfl 0.4 --lengths 2 2 --n 400 400 \
    --fixed-dt --mesh dy=2 --impl pallas \
    --save out/multigpu_burgers2d "$@"
