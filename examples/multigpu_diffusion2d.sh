#!/bin/sh
# MultiGPU/Diffusion2d_Baseline/run.sh: K=1, L=W=2, 400x400, 1000 iters, 2 ranks
python -m multigpu_advectiondiffusion_tpu.cli diffusion2d \
    --K 1.0 --lengths 2 2 --n 400 400 --iters 1000 \
    --mesh dy=2 --impl pallas --save out/multigpu_diffusion2d "$@"
