#!/bin/sh
# The mpirun analog through the CLI: MultiGPU/Diffusion3d_Baseline/run.sh
# (`mpirun -np 2 ./Diffusion3d.run 1.00 2.00 2.00 2.00 400 200 200 1000
# 64 4 1`) as two cooperating CLI processes joined by jax.distributed.
# Run ONE copy of this block per host (here: both locally for a demo),
# same --coordinator/--num-processes, unique --process-id. The compound
# mesh axis dz_dcn=2,dz_ici=N puts the slab's DCN hop between process
# granules and the ICI hops inside each host; the fused per-stage
# kernels run shard-local with the overlapped halo schedule, and the
# coordinator writes initial.bin/result.bin/summary.json from gathered
# shards. N must match each host's local chip count.
#
# Demo on one machine with virtual CPU devices:
#   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
#     sh examples/multihost_diffusion3d.sh --impl xla --n 64 32 32 --iters 10
PORT=${PORT:-12357}
for PID in 0 1; do
  python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
      --K 1.0 --lengths 2 2 2 --n 400 200 200 --iters 1000 \
      --mesh dz_dcn=2,dz_ici=4 --impl pallas --overlap split \
      --coordinator localhost:$PORT --num-processes 2 --process-id $PID \
      --checkpoint-every 500 --checkpoint-sharded \
      --sentinel-every 500 --watchdog-timeout 60 \
      --save out/multihost_diffusion3d "$@" &
done
wait
