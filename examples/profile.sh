#!/bin/sh
# MultiGPU/Diffusion3d_Baseline/profile.sh: per-rank nvprof wrap ->
# one jax.profiler device trace (TensorBoard/Perfetto viewable).
python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
    --K 1.0 --lengths 2 2 2 --n 400 200 200 --iters 100 \
    --profile out/trace "$@"
