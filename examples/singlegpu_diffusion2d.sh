#!/bin/sh
# SingleGPU/Diffusion2d/run.sh: K=1, 10x10 domain, 1001^2, 10000 iters
python -m multigpu_advectiondiffusion_tpu.cli diffusion2d \
    --K 1.0 --lengths 10 10 --n 1001 1001 --iters 10000 \
    --impl pallas --save out/singlegpu_diffusion2d "$@"
