#!/bin/sh
# SingleGPU/RunAll.m: batch over the whole variant ladder -> the
# benchmark matrix sweeps every reference config and records MLUPS
# next to the archived Run.m numbers.
python -m multigpu_advectiondiffusion_tpu.bench --out out/bench.jsonl "$@"
