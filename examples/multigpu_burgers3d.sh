#!/bin/sh
# MultiGPU/Burgers3d_Baseline/run.sh: tEnd=0.4 CFL=0.3, 2x2x4 domain, 200^3, 2 ranks.
# --fixed-dt reproduces the CUDA drivers' hard-coded unit wave speed;
# drop it to restore the correct adaptive dt (real global max reduction).
# --impl pallas --overlap split = the tuned fused kernel with the overlapped
# halo schedule, in the drivers' native while-t<tEnd mode.
# Without TPU hardware append --impl xla (CPU runs Pallas interpreted).
python -m multigpu_advectiondiffusion_tpu.cli burgers3d \
    --t-end 0.4 --cfl 0.3 --lengths 2 2 4 --n 200 200 200 \
    --impl pallas --overlap split \
    --fixed-dt --mesh dz=2 --save out/multigpu_burgers3d "$@"
