#!/bin/sh
# Matlab_Prototipes/InviscidBurgersNd/LFWENO7FDM3d.m: 100^3 cells on
# [-1,1]^3, CFL=0.4, tEnd=0.4, burgers flux, gaussian IC exp(-r^2/0.1)
# (the CLI's `gaussian` default), real adaptive dt (the MATLAB
# prototypes never hard-code max|u|). Order 7 engages the halo-4 fused
# stepper. The reference never ported WENO7 off MATLAB, so there is no
# run.sh to mirror — this maps the .m driver itself, with one
# DELIBERATE deviation: LFWENO7FDM3d.m integrates with a 5-stage
# low-storage RK4 (rk4a/rk4b), while this config runs the framework's
# SSP-RK3 (the only integrator the fused steppers serve). Space
# discretization and dt rule are the prototype's; trajectories agree to
# the integrators' order, not bit-for-bit (recorded like the other
# known deviations in PARITY.md).
python -m multigpu_advectiondiffusion_tpu.cli burgers3d \
    --weno-order 7 --t-end 0.4 --cfl 0.4 --lengths 2 2 2 \
    --n 100 100 100 --impl pallas \
    --save out/matlab_weno7_3d "$@"
