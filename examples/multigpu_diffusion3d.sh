#!/bin/sh
# MultiGPU/Diffusion3d_Baseline/run.sh: K=1, L=W=2 H=2, 400x200x200, 1000 iters, 2 ranks.
# --impl pallas --overlap split = the tuned fused kernel with the overlapped
# halo schedule (the reference's five-stream choreography is always on).
# Without TPU hardware append --impl xla (CPU runs Pallas interpreted).
python -m multigpu_advectiondiffusion_tpu.cli diffusion3d \
    --K 1.0 --lengths 2 2 2 --n 400 200 200 --iters 1000 \
    --impl pallas --overlap split \
    --mesh dz=2 --save out/multigpu_diffusion3d "$@"
