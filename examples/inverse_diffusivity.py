"""Gradient-based inverse problem on the batched ensemble engine.

Recover an unknown diffusivity K* from one observed field by
differentiating STRAIGHT THROUGH the batched dispatch: ``jax.grad``
flows through ``SolverBase.advance_to_ensemble`` (the ``max_steps``
bounded-loop mode — reverse-mode needs a static trip count) with the
member diffusivities as traced operands, so one compiled program
yields the loss AND its gradient for B independent optimization
trajectories at once. This is a scenario family the CUDA reference can
never offer (ROADMAP item 1's creative extension): its kernels are
hand-written forward passes; here the same vmapped stepper that serves
the ensemble engine is differentiable for free.

Run::

    JAX_PLATFORMS=cpu python examples/inverse_diffusivity.py

Consumed by ``tests/test_inverse.py`` (tier-1, loose-tolerance
convergence assert).
"""

from __future__ import annotations

import os
import sys

# runnable as a plain script from anywhere: the package lives one
# directory up from examples/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def make_problem(n: int = 48, k_true: float = 1.0, t_window: float = 0.05):
    """(solver, batched initial state template, t_end, observed field)
    for a 1-D heat-kernel workload with ground-truth diffusivity
    ``k_true``."""
    from multigpu_advectiondiffusion_tpu import (
        DiffusionConfig,
        DiffusionSolver,
        Grid,
    )

    grid = Grid.make(n, lengths=10.0)
    cfg = DiffusionConfig(grid=grid, diffusivity=k_true, dtype="float32",
                          impl="xla")
    solver = DiffusionSolver(cfg)
    s0 = solver.initial_state()
    t_end = float(s0.t) + t_window
    obs = solver.advance_to(s0, t_end)
    return solver, s0, t_end, obs.u


def recover_diffusivity(
    guesses,
    n: int = 48,
    k_true: float = 1.0,
    t_window: float = 0.05,
    iterations: int = 60,
    lr: float = 0.05,
    max_steps: int = 64,
):
    """Run B simultaneous gradient-descent trajectories (one per initial
    guess) against the observed field; returns ``(recovered, history)``
    where ``recovered`` is the (B,) final diffusivity estimates.

    ``max_steps`` bounds every member's step count for the
    differentiable ``fori_loop`` mode of ``advance_to_ensemble`` — it
    must cover the steepest member (largest K => smallest stability
    dt => most steps to ``t_end``)."""
    from multigpu_advectiondiffusion_tpu.models.state import EnsembleState

    solver, s0, t_end, u_obs = make_problem(n, k_true, t_window)
    Ks = jnp.asarray(guesses, jnp.float32)
    B = int(Ks.shape[0])
    est0 = EnsembleState(
        u=jnp.stack([s0.u] * B),
        t=jnp.stack([s0.t] * B),
        it=jnp.zeros((B,), jnp.int32),
    )

    def loss(ks):
        out = solver.advance_to_ensemble(
            est0, t_end, operands={"diffusivity": ks},
            max_steps=max_steps,
        )
        # summed per-member misfits: members are independent, so the
        # gradient separates — one backward pass serves all B
        # optimization trajectories
        return jnp.sum(jnp.mean((out.u - u_obs[None]) ** 2, axis=1))

    grad_fn = jax.value_and_grad(loss)
    history = []
    # sign descent on log K with a geometrically decaying step: the
    # per-member misfit scales differ by orders of magnitude across
    # guesses (a raw gradient step would stall the flattest member);
    # the decaying log-step first homes in at a fixed multiplicative
    # rate, then anneals — total travel covers a ~10x-off guess
    theta = jnp.log(Ks)
    step = lr
    for _ in range(iterations):
        value, grads = grad_fn(jnp.exp(theta))
        history.append(float(value))
        theta = theta - step * jnp.sign(grads)
        step *= 0.97
    Ks = jnp.exp(theta)
    return Ks, history


def main():
    k_true = 1.3
    guesses = [0.4, 0.9, 2.2, 3.5]
    recovered, history = recover_diffusivity(guesses, k_true=k_true)
    print(f"true diffusivity: {k_true}")
    for g, k in zip(guesses, [float(v) for v in recovered]):
        err = abs(k - k_true) / k_true
        print(f"  guess {g:4.2f} -> recovered {k:6.4f} "
              f"(rel err {100 * err:.2f}%)")
    print(f"loss: {history[0]:.3e} -> {history[-1]:.3e} "
          f"({len(history)} gradient steps through the batched "
          "dispatch)")


if __name__ == "__main__":
    main()
