#!/bin/sh
# SingleGPU/Burgers3d_WENO5/run.sh: tEnd=0.1 CFL=0.3, 2^3 domain, 1000x1000x200
# (viscous, nu=1e-5, like the single-GPU variants)
python -m multigpu_advectiondiffusion_tpu.cli burgers3d \
    --t-end 0.1 --cfl 0.3 --lengths 2 2 2 --n 1000 1000 200 \
    --nu 1e-5 --fixed-dt --save out/singlegpu_burgers3d "$@"
