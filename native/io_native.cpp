// Native IO runtime for multigpu_advectiondiffusion_tpu.
//
// TPU-native equivalent of the reference's host-side IO/tooling layer
// (MultiGPU/Diffusion3d_Baseline/Tools.c: SaveBinary3D :91-119, Save3D
// ASCII :68-86, Merge_domains :204-223). The reference writes float32
// binaries synchronously on rank 0 after a hand-rolled MPI gather; here
// the writer is a small C library driven from Python via ctypes: the
// double-buffered async writer lets the solver keep stepping while the
// previous snapshot drains to disk (the role the reference's pinned host
// buffers + DtH copies played for output).
//
// Build: make -C native    (produces libtpucfd_io.so)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Synchronous float32 raw writer (SaveBinary3D layout: x fastest).
// Returns 0 on success, -1 on failure.
// ---------------------------------------------------------------------
int save_binary_f32(const char* path, const float* data, size_t count) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  size_t written = std::fwrite(data, sizeof(float), count, f);
  int rc = (written == count) ? 0 : -1;
  if (std::fclose(f) != 0) rc = -1;
  return rc;
}

// ---------------------------------------------------------------------
// Synchronous ASCII writer (Save3D layout: one %g per line).
// ---------------------------------------------------------------------
int save_ascii_f64(const char* path, const double* data, size_t count) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  for (size_t i = 0; i < count; ++i) {
    if (std::fprintf(f, "%g\n", data[i]) < 0) {
      std::fclose(f);
      return -1;
    }
  }
  return std::fclose(f) == 0 ? 0 : -1;
}

int load_binary_f32(const char* path, float* out, size_t count) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  size_t got = std::fread(out, sizeof(float), count, f);
  std::fclose(f);
  return got == count ? 0 : -1;
}

// ---------------------------------------------------------------------
// Async double-buffered writer.
//
// writer_create(n) -> handle with n queue slots; writer_submit copies the
// snapshot into an owned buffer and returns immediately; a background
// thread drains the queue. writer_flush blocks until empty;
// writer_destroy flushes and frees. All functions return 0 on success.
// ---------------------------------------------------------------------
namespace {

struct Job {
  std::string path;
  std::vector<float> data;
};

struct Writer {
  std::thread thread;
  std::mutex mu;
  std::condition_variable cv_push, cv_done;
  std::queue<Job> jobs;
  size_t max_queue;
  std::atomic<int> error{0};
  bool stop = false;
  size_t in_flight = 0;

  explicit Writer(size_t slots) : max_queue(slots ? slots : 1) {
    thread = std::thread([this] { run(); });
  }

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_push.wait(lk, [this] { return stop || !jobs.empty(); });
      if (jobs.empty()) {
        if (stop) return;
        continue;
      }
      Job job = std::move(jobs.front());
      jobs.pop();
      ++in_flight;
      lk.unlock();
      if (save_binary_f32(job.path.c_str(), job.data.data(),
                          job.data.size()) != 0) {
        error.store(-1);
      }
      lk.lock();
      --in_flight;
      cv_done.notify_all();
    }
  }

  int submit(const char* path, const float* data, size_t count) {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return jobs.size() < max_queue; });
    Job job;
    job.path = path;
    job.data.assign(data, data + count);
    jobs.push(std::move(job));
    cv_push.notify_one();
    return error.load();
  }

  int flush() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return jobs.empty() && in_flight == 0; });
    return error.load();
  }

  ~Writer() {
    {
      std::unique_lock<std::mutex> lk(mu);
      cv_done.wait(lk, [this] { return jobs.empty() && in_flight == 0; });
      stop = true;
      cv_push.notify_all();
    }
    thread.join();
  }
};

}  // namespace

void* writer_create(size_t queue_slots) { return new Writer(queue_slots); }

int writer_submit(void* handle, const char* path, const float* data,
                  size_t count) {
  return static_cast<Writer*>(handle)->submit(path, data, count);
}

int writer_flush(void* handle) {
  return static_cast<Writer*>(handle)->flush();
}

void writer_destroy(void* handle) { delete static_cast<Writer*>(handle); }

}  // extern "C"
