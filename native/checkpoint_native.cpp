// Native checkpoint runtime for multigpu_advectiondiffusion_tpu.
//
// The reference has no restart capability at all (SURVEY §5: only the IC
// write and the final result write, MultiGPU/Diffusion3d_Baseline/
// main.c:82-86,339-343). This module provides the framework's checkpoint
// format as a small C library:
//
//   * self-describing 64-byte header (magic, version, dtype, shape, t,
//     iteration) + raw payload,
//   * CRC32 (zlib polynomial — verifiable from Python's zlib.crc32) over
//     the payload, checked on load,
//   * atomic persistence: write to "<path>.tmp", flush, fsync, rename —
//     a crash mid-write can never leave a truncated file at the final
//     path.
//
// utils/io.py mirrors the exact byte layout in numpy so the format is
// identical whether or not this library is built.
//
// Build: make -C native    (part of libtpucfd_io.so)

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#ifdef _WIN32
#error "POSIX only"
#endif
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'T', 'P', 'C', 'F', 'D', 'C', 'K', 'P'};
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderBytes = 64;
constexpr uint32_t kMaxNdim = 4;

// zlib CRC32 (polynomial 0xEDB88320), table-driven.
const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  return table;
}

uint32_t crc32_update(uint32_t crc, const uint8_t* buf, size_t len) {
  const uint32_t* table = crc_table();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    crc = table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

struct Header {
  char magic[8];        // offset  0
  uint32_t version;     // offset  8
  uint32_t dtype_code;  // offset 12: 0 = f32, 1 = f64
  uint32_t ndim;        // offset 16
  uint32_t shape[kMaxNdim];  // offset 20
  uint8_t pad_[4];      // offset 36 (keeps t 8-aligned, explicit)
  double t;             // offset 40
  int64_t it;           // offset 48
  uint32_t payload_crc32;  // offset 56
  uint8_t reserved[4];  // offset 60
};
static_assert(sizeof(Header) == kHeaderBytes, "header layout drifted");

size_t dtype_size(uint32_t code) {
  return code == 0 ? 4 : code == 1 ? 8 : 0;
}

}  // namespace

extern "C" {

// Returns 0 on success, -1 on IO/argument failure.
int checkpoint_save(const char* path, const void* data, uint32_t dtype_code,
                    uint32_t ndim, const uint32_t* shape, double t,
                    int64_t it) {
  size_t item = dtype_size(dtype_code);
  if (!item || ndim == 0 || ndim > kMaxNdim) return -1;
  size_t count = 1;
  Header h{};
  std::memcpy(h.magic, kMagic, sizeof(kMagic));
  h.version = kVersion;
  h.dtype_code = dtype_code;
  h.ndim = ndim;
  for (uint32_t d = 0; d < kMaxNdim; ++d) {
    h.shape[d] = d < ndim ? shape[d] : 1;
    count *= h.shape[d];
  }
  h.t = t;
  h.it = it;
  size_t nbytes = count * item;
  h.payload_crc32 =
      crc32_update(0, static_cast<const uint8_t*>(data), nbytes);

  std::string tmp = std::string(path) + ".tmp";
  FILE* f = std::fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  bool ok = std::fwrite(&h, 1, kHeaderBytes, f) == kHeaderBytes &&
            std::fwrite(data, 1, nbytes, f) == nbytes &&
            std::fflush(f) == 0 && fsync(fileno(f)) == 0;
  ok = (std::fclose(f) == 0) && ok;
  if (!ok || std::rename(tmp.c_str(), path) != 0) {
    std::remove(tmp.c_str());
    return -1;
  }
  return 0;
}

// Reads header only. Returns 0 ok, -1 IO error, -3 bad magic/version.
int checkpoint_load_header(const char* path, uint32_t* dtype_code,
                           uint32_t* ndim, uint32_t* shape /* [4] */,
                           double* t, int64_t* it) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  Header h{};
  size_t got = std::fread(&h, 1, kHeaderBytes, f);
  std::fclose(f);
  if (got != kHeaderBytes) return -1;
  if (std::memcmp(h.magic, kMagic, sizeof(kMagic)) != 0 ||
      h.version != kVersion || !dtype_size(h.dtype_code) || h.ndim == 0 ||
      h.ndim > kMaxNdim)
    return -3;
  *dtype_code = h.dtype_code;
  *ndim = h.ndim;
  for (uint32_t d = 0; d < kMaxNdim; ++d) shape[d] = h.shape[d];
  *t = h.t;
  *it = h.it;
  return 0;
}

// Reads and CRC-verifies the payload (caller sizes `out` from the
// header). Returns 0 ok, -1 IO error, -2 CRC mismatch, -3 bad magic.
int checkpoint_load_payload(const char* path, void* out, size_t nbytes) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  Header h{};
  if (std::fread(&h, 1, kHeaderBytes, f) != kHeaderBytes) {
    std::fclose(f);
    return -1;
  }
  if (std::memcmp(h.magic, kMagic, sizeof(kMagic)) != 0 ||
      h.version != kVersion) {
    std::fclose(f);
    return -3;
  }
  size_t got = std::fread(out, 1, nbytes, f);
  std::fclose(f);
  if (got != nbytes) return -1;
  uint32_t crc = crc32_update(0, static_cast<const uint8_t*>(out), nbytes);
  return crc == h.payload_crc32 ? 0 : -2;
}

}  // extern "C"
